"""Numeric validation for the final op-widening families (ops/wide_defs.py).

Updater ops are checked against the framework's own train/updaters.py (which
is itself trajectory-tested against the reference's update rules); CTC loss
against a brute-force path enumeration; the rest against numpy oracles.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import ops
from deeplearning4j_tpu.ops import mark_validated

RNG = np.random.default_rng(11)


def _np(x):
    return np.asarray(x.toNumpy() if hasattr(x, "toNumpy") else x)


class TestUpdaterOps:
    def test_sgd(self):
        g = jnp.ones(4)
        np.testing.assert_allclose(_np(ops.updaters.sgdUpdater(g, lr=0.5)), 0.5)
        mark_validated("sgdUpdater", "updaters")

    def test_adam_matches_closed_form_first_step(self):
        g = jnp.asarray(RNG.normal(size=5).astype(np.float32))
        upd, m, v, t = ops.updaters.adamUpdater(g, jnp.zeros(5), jnp.zeros(5), 0,
                                                lr=1e-3)
        # first Adam step is lr * sign-ish: m_hat = g, v_hat = g^2
        want = 1e-3 * _np(g) / (np.abs(_np(g)) + 1e-8)
        np.testing.assert_allclose(_np(upd), want, rtol=1e-5)
        assert int(_np(t)) == 1
        mark_validated("adamUpdater", "updaters")

    def test_nesterovs_momentum_accumulates(self):
        g = jnp.ones(3)
        upd1, v1 = ops.updaters.nesterovsUpdater(g, jnp.zeros(3), lr=0.1,
                                                 momentum=0.9)
        upd2, v2 = ops.updaters.nesterovsUpdater(g, v1, lr=0.1, momentum=0.9)
        assert _np(upd2)[0] > _np(upd1)[0]  # momentum grows the step
        mark_validated("nesterovsUpdater", "updaters")

    def test_amsgrad_first_step_matches_closed_form(self):
        # first step: m=(1-b1)g, vhat=(1-b2)g^2 -> update ~= lr*sign(g)
        g = jnp.asarray(RNG.normal(size=5).astype(np.float32))
        z = jnp.zeros(5)
        upd, m, v, vh, t = ops.updaters.amsGradUpdater(g, z, z, z, 0, lr=1e-3)
        want = 1e-3 * _np(g) / (np.abs(_np(g)) + 1e-8 / np.sqrt(1 - 0.999))
        np.testing.assert_allclose(_np(upd), want, rtol=1e-4)
        mark_validated("amsGradUpdater", "updaters")

    def test_stateful_updaters_return_new_state(self):
        g = jnp.asarray(RNG.normal(size=4).astype(np.float32))
        z = jnp.zeros(4)
        for name, args in [
            ("adaGradUpdater", (g, z)),
            ("rmsPropUpdater", (g, z)),
            ("adaDeltaUpdater", (g, z, z)),
            ("adaMaxUpdater", (g, z, z, 0)),
            ("nadamUpdater", (g, z, z, 0)),
            ("amsGradUpdater", (g, z, z, z, 0)),
            ("adaBeliefUpdater", (g, z, z, 0)),
        ]:
            out = getattr(ops.updaters, name)(*args)
            upd = out[0]
            assert np.all(np.isfinite(_np(upd))), name
            # descent direction: update has the same sign as the gradient
            nz = np.abs(_np(g)) > 1e-6
            assert np.all(np.sign(_np(upd))[nz] == np.sign(_np(g))[nz]), name
            mark_validated(name, "updaters")


class TestBooleanChecks:
    def test_monotonic(self):
        assert bool(ops.math.isNonDecreasing(jnp.array([1.0, 1.0, 2.0])))
        assert not bool(ops.math.isStrictlyIncreasing(jnp.array([1.0, 1.0])))
        assert bool(ops.math.isStrictlyIncreasing(jnp.array([1.0, 3.0])))
        assert ops.math.isNumericTensor(jnp.array([1.0]))
        for k in ["isNonDecreasing", "isStrictlyIncreasing", "isNumericTensor"]:
            mark_validated(k, "math")


class TestParityStragglers:
    def test_stop_gradient_blocks_grad(self):
        from deeplearning4j_tpu.ops import REGISTRY
        sg = REGISTRY["math.stopGradient"].fn
        g = jax.grad(lambda x: jnp.sum(sg(x) * x))(jnp.ones(3))
        np.testing.assert_allclose(np.asarray(g), 1.0)  # d(sg(x)*x)/dx = sg(x)
        mark_validated("stopGradient", "math")

    def test_divide_no_nan(self):
        got = _np(ops.math.divideNoNan(jnp.array([1.0, 2.0]), jnp.array([0.0, 4.0])))
        np.testing.assert_allclose(got, [0.0, 0.5])
        mark_validated("divideNoNan", "math")

    def test_cummax_cummin(self):
        x = np.array([3.0, 1.0, 4.0, 1.0, 5.0])
        np.testing.assert_allclose(_np(ops.math.cummax(x)), np.maximum.accumulate(x))
        np.testing.assert_allclose(_np(ops.math.cummin(x)), np.minimum.accumulate(x))
        mark_validated("cummax", "math"); mark_validated("cummin", "math")

    def test_mirror_pad_and_bias_add(self):
        x = np.arange(4.0).reshape(2, 2)
        got = _np(ops.shape.mirrorPad(x, [(1, 1), (0, 0)], mode="REFLECT"))
        np.testing.assert_allclose(got[0], x[1])
        b = np.array([1.0, -1.0])
        nchw = _np(ops.nn.biasAdd(np.zeros((1, 2, 3, 3)), b, data_format="NCHW"))
        assert nchw[0, 0, 0, 0] == 1.0 and nchw[0, 1, 0, 0] == -1.0
        mark_validated("mirrorPad", "shape"); mark_validated("biasAdd", "nn")

    def test_matrix_set_diag(self):
        x = np.zeros((2, 3, 3), np.float32)
        got = _np(ops.linalg.matrixSetDiag(x, np.ones((2, 3), np.float32)))
        np.testing.assert_allclose(got[0], np.eye(3))
        mark_validated("matrixSetDiag", "linalg")

    def test_space_to_batch_roundtrip(self):
        x = RNG.normal(size=(2, 4, 6, 3)).astype(np.float32)
        s2b = ops.cnn.spaceToBatchNd(x, [2, 2], [[0, 0], [0, 0]])
        assert _np(s2b).shape == (8, 2, 3, 3)
        back = ops.cnn.batchToSpaceNd(_np(s2b), [2, 2], [[0, 0], [0, 0]])
        np.testing.assert_allclose(_np(back), x, rtol=1e-6)
        mark_validated("spaceToBatchNd", "cnn")
        mark_validated("batchToSpaceNd", "cnn")

    def test_nth_element_select_sparse(self):
        x = np.array([5.0, 2.0, 9.0, 1.0])
        assert float(_np(ops.math.nthElement(x, 1))) == 2.0
        assert float(_np(ops.math.nthElement(x, 0, reverse=True))) == 9.0
        np.testing.assert_allclose(
            _np(ops.shape.select(np.array([True, False]), 1.0, 2.0)), [1.0, 2.0])
        dense = _np(ops.shape.sparseToDense(np.array([[0, 1]]), (2, 2),
                                            np.array([7.0])))
        assert dense[0, 1] == 7.0 and dense[1, 1] == 0.0
        for k in ["nthElement"]:
            mark_validated(k, "math")
        for k in ["select", "sparseToDense"]:
            mark_validated(k, "shape")

    def test_histogram_and_sufficient_statistics(self):
        x = np.array([0.0, 0.1, 0.9, 1.0])
        h = _np(ops.math.histogram(x, bins=2))
        np.testing.assert_array_equal(h, [2, 2])
        cnt, s, s2 = ops.math.sufficientStatistics(np.ones((2, 3)), axes=(0, 1))
        assert float(_np(cnt)) == 6.0 and float(_np(s)) == 6.0 and float(_np(s2)) == 6.0
        mark_validated("histogram", "math")
        mark_validated("sufficientStatistics", "math")

    def test_split_v_and_intersection(self):
        parts = ops.shape.splitV(np.arange(10), [3, 3, 4])
        assert [len(_np(p)) for p in parts] == [3, 3, 4]
        np.testing.assert_array_equal(
            _np(ops.shape.intersection(np.array([1, 2, 3]), np.array([2, 3, 4]))),
            [2, 3])
        mark_validated("splitV", "shape"); mark_validated("intersection", "shape")

    def test_oneliner_transforms(self):
        x = np.array([3.0, -4.0], np.float32)
        np.testing.assert_allclose(_np(ops.math.assign(x, 7.0)), [7.0, 7.0])
        np.testing.assert_allclose(_np(ops.math.axpy(x, np.ones(2), alpha=2.0)),
                                   [7.0, -7.0])
        np.testing.assert_allclose(_np(ops.math.realDiv(np.array([7]), np.array([2]))), 3.5)
        np.testing.assert_allclose(_np(ops.math.truncateDiv(np.array([-7.0]), np.array([2.0]))), -3.0)
        np.testing.assert_allclose(_np(ops.math.trigamma(np.array([1.0]))),
                                   np.pi ** 2 / 6, rtol=1e-5)
        assert float(_np(ops.math.nextafter(np.float32(1.0), np.float32(2.0)))) > 1.0
        assert tuple(ops.shape.broadcastShape((3, 1), (1, 4))) == (3, 4)
        for k in ["assign", "axpy", "realDiv", "truncateDiv", "trigamma",
                  "nextafter"]:
            mark_validated(k, "math")
        mark_validated("broadcastShape", "shape")

    def test_check_numerics_raises(self):
        with pytest.raises(FloatingPointError):
            ops.math.checkNumerics(np.array([1.0, np.nan]))
        np.testing.assert_allclose(_np(ops.math.checkNumerics(np.ones(2))), 1.0)
        mark_validated("checkNumerics", "math")


class TestTsneOps:
    def test_gains_rule(self):
        gains = np.ones(3)
        got = _np(ops.math.tsneGains(gains, np.array([1.0, -1.0, 1.0]),
                                     np.array([1.0, 1.0, -1.0])))
        np.testing.assert_allclose(got, [0.8, 1.2, 1.2])
        mark_validated("tsneGains", "math")

    def test_symmetrized_is_symmetric_prob(self):
        p = np.abs(RNG.normal(size=(4, 4))).astype(np.float32)
        s = _np(ops.math.tsneSymmetrized(p))
        np.testing.assert_allclose(s, s.T, rtol=1e-6)
        assert abs(s.sum() - 1.0) < 1e-5
        mark_validated("tsneSymmetrized", "math")

    def test_edge_forces_pull_together(self):
        y = np.array([[0.0, 0.0], [1.0, 0.0]], np.float32)
        p = np.array([[0.0, 1.0], [1.0, 0.0]], np.float32)
        f = _np(ops.math.tsneEdgeForces(y, p))
        assert f[0, 0] < 0 and f[1, 0] > 0  # attraction along x
        assert bool(_np(ops.math.tsneCellContains(
            np.zeros(2), np.ones(2), np.array([0.5, 0.5]))))
        mark_validated("tsneEdgeForces", "math")
        mark_validated("tsneCellContains", "math")


class TestBitmapCompression:
    def test_roundtrip_with_residual(self):
        x = np.array([0.5, -0.3, 0.05, -0.9], np.float32)
        code, residual = ops.math.encodeBitmap(x, 0.2)
        np.testing.assert_array_equal(_np(code), [1, -1, 0, -1])
        dec = _np(ops.math.decodeBitmap(_np(code), 0.2))
        np.testing.assert_allclose(dec + _np(residual), x, rtol=1e-6)
        mark_validated("encodeBitmap", "math")
        mark_validated("decodeBitmap", "math")


class TestRecurrentVariants:
    def test_lstm_block_shapes_and_forget_bias(self):
        B, T, I, H = 2, 5, 3, 4
        x = jnp.asarray(RNG.normal(size=(T, B, I)).astype(np.float32))
        w = jnp.asarray(RNG.normal(size=(I + H, 4 * H)).astype(np.float32) * 0.1)
        b = jnp.zeros(4 * H)
        hs, c_fin, h_fin = ops.rnn.lstmBlock(x, jnp.zeros((B, H)), jnp.zeros((B, H)), w, b)
        assert _np(hs).shape == (T, B, H)
        assert np.all(np.isfinite(_np(c_fin)))
        mark_validated("lstmBlock", "rnn"); mark_validated("lstmBlockCell", "rnn")

    def test_dynamic_rnn_respects_lengths(self):
        B, T, I, H = 2, 6, 3, 4
        x = jnp.asarray(RNG.normal(size=(B, T, I)).astype(np.float32))
        w_ih = jnp.asarray(RNG.normal(size=(I, H)).astype(np.float32) * 0.3)
        w_hh = jnp.asarray(RNG.normal(size=(H, H)).astype(np.float32) * 0.3)
        b = jnp.zeros(H)
        hs, h_fin = ops.rnn.dynamicRnn(x, jnp.zeros((B, H)), w_ih, w_hh, b,
                                       seq_lengths=np.array([3, 6]))
        hs = _np(hs)
        # TF dynamic_rnn semantics: outputs past each length are ZERO, while
        # the carried final state holds the last valid hidden state
        np.testing.assert_allclose(hs[0, 3], np.zeros_like(hs[0, 3]))
        np.testing.assert_allclose(hs[0, 5], np.zeros_like(hs[0, 5]))
        assert not np.allclose(hs[0, 2], 0.0)
        assert not np.allclose(hs[1, 5], hs[1, 2])
        np.testing.assert_allclose(_np(h_fin)[0], hs[0, 2], rtol=1e-6)
        mark_validated("dynamicRnn", "rnn"); mark_validated("staticRnn", "rnn")

    def test_bidirectional_concat(self):
        B, T, I, H = 2, 4, 3, 5
        x = jnp.asarray(RNG.normal(size=(B, T, I)).astype(np.float32))
        mk = lambda *s: jnp.asarray(RNG.normal(size=s).astype(np.float32) * 0.2)
        hs, hf, hb = ops.rnn.dynamicBidirectionalRnn(
            x, jnp.zeros((B, H)), jnp.zeros((B, H)),
            mk(I, H), mk(H, H), jnp.zeros(H), mk(I, H), mk(H, H), jnp.zeros(H))
        assert _np(hs).shape == (B, T, 2 * H)
        mark_validated("dynamicBidirectionalRnn", "rnn")

    def test_bidirectional_ragged_ignores_padding(self):
        B, T, I, H = 2, 4, 3, 2
        RNGL = np.random.default_rng(5)
        x = RNGL.normal(size=(B, T, I)).astype(np.float32)
        x[0, 2:] = 99.0  # padding frames for example 0 (len 2)
        mk = lambda *s: jnp.asarray(RNGL.normal(size=s).astype(np.float32) * 0.2)
        args = (jnp.zeros((B, H)), jnp.zeros((B, H)),
                mk(I, H), mk(H, H), jnp.zeros(H), mk(I, H), mk(H, H), jnp.zeros(H))
        hs1, hf1, hb1 = ops.rnn.dynamicBidirectionalRnn(
            jnp.asarray(x), *args, seq_lengths=np.array([2, 4]))
        x2 = x.copy(); x2[0, 2:] = -77.0  # different padding, same real frames
        hs2, hf2, hb2 = ops.rnn.dynamicBidirectionalRnn(
            jnp.asarray(x2), *args, seq_lengths=np.array([2, 4]))
        # backward final state must be a function of the real frames only
        np.testing.assert_allclose(_np(hb1), _np(hb2), rtol=1e-6)
        np.testing.assert_allclose(_np(hs1)[0, :2], _np(hs2)[0, :2], rtol=1e-6)


class TestImageStragglers:
    def test_nms_overlaps(self):
        # two overlapping boxes + one separate
        overlaps = np.array([[1.0, 0.8, 0.0],
                             [0.8, 1.0, 0.0],
                             [0.0, 0.0, 1.0]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        sel = _np(ops.image.nonMaxSuppressionOverlaps(overlaps, scores, 3, 0.5))
        assert sel[0] == 0 and 2 in sel.tolist() and 1 not in sel.tolist()
        mark_validated("nonMaxSuppressionOverlaps", "image")

    def test_draw_bounding_boxes_marks_border(self):
        img = np.zeros((1, 8, 8, 3), np.float32)
        boxes = np.array([[[0.25, 0.25, 0.75, 0.75]]], np.float32)
        out = _np(ops.image.drawBoundingBoxes(img, boxes))
        assert out[0, 2, 2].sum() > 0        # corner painted
        assert out[0, 4, 4].sum() == 0       # interior untouched
        mark_validated("drawBoundingBoxes", "image")

    def test_adjust_gamma(self):
        img = np.full((2, 2), 0.25, np.float32)
        np.testing.assert_allclose(_np(ops.image.adjustGamma(img, gamma=0.5)), 0.5)
        mark_validated("adjustGamma", "image")


class TestCnnStragglers:
    def test_pnorm_pool_p2_matches_norm(self):
        x = np.abs(RNG.normal(size=(1, 1, 4, 4))).astype(np.float32)
        got = _np(ops.cnn.pnormPool2d(x, window=(2, 2), p=2.0))
        want = np.zeros((1, 1, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                want[0, 0, i, j] = np.linalg.norm(
                    x[0, 0, 2*i:2*i+2, 2*j:2*j+2].ravel())
        np.testing.assert_allclose(got, want, rtol=1e-5)
        mark_validated("pnormPool2d", "cnn")

    def test_deconv3d_shape(self):
        x = jnp.zeros((1, 2, 3, 3, 3))
        w = jnp.zeros((2, 2, 2, 4, 2))  # kD,kH,kW,Cout,Cin
        out = ops.cnn.deconv3d(x, w, strides=(2, 2, 2))
        assert _np(out).shape == (1, 4, 6, 6, 6)
        mark_validated("deconv3d", "cnn")


def _brute_force_ctc(logp, target, blank=0):
    """Sum over all alignments by dynamic programming on paths (tiny T,V)."""
    import itertools
    T, V = logp.shape
    total = -np.inf
    for path in itertools.product(range(V), repeat=T):
        # collapse repeats then remove blanks
        collapsed = []
        prev = None
        for s in path:
            if s != prev:
                collapsed.append(s)
            prev = s
        collapsed = [s for s in collapsed if s != blank]
        if collapsed == list(target):
            lp = sum(logp[t, path[t]] for t in range(T))
            total = np.logaddexp(total, lp)
    return -total


class TestLossStragglers:
    def test_ctc_matches_brute_force(self):
        T, V = 4, 3
        logits = RNG.normal(size=(1, T, V)).astype(np.float64)
        logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits)))
        target = [1, 2]
        got = float(_np(ops.loss.ctcLoss(logp, np.array([target]),
                                         np.array([T]), np.array([2]))))
        want = _brute_force_ctc(logp[0], target)
        assert got == pytest.approx(want, rel=1e-4)
        mark_validated("ctcLoss", "loss")

    def test_weighted_xent_reduces_to_plain_at_w1(self):
        t = np.array([0.0, 1.0], np.float32)
        z = np.array([0.3, -0.4], np.float32)
        got = _np(ops.loss.weightedCrossEntropyWithLogits(t, z, pos_weight=1.0))
        want = np.maximum(z, 0) - z * t + np.log1p(np.exp(-np.abs(z)))
        np.testing.assert_allclose(got, want, rtol=1e-5)
        mark_validated("weightedCrossEntropyWithLogits", "loss")

    def test_mean_pairwise_squared_error_zero_for_uniform_shift(self):
        lab = RNG.normal(size=(3, 4)).astype(np.float32)
        pred = lab + 2.5  # uniform shift -> pairwise differences unchanged
        got = float(_np(ops.loss.meanPairwiseSquaredError(lab, pred)))
        assert got == pytest.approx(0.0, abs=1e-4)
        mark_validated("meanPairwiseSquaredError", "loss")


class TestRandomExtras:
    def test_lognormal_positive(self):
        key = jax.random.PRNGKey(0)
        x = _np(ops.random.lognormal(key, (1000,)))
        assert np.all(x > 0)
        assert abs(np.median(x) - 1.0) < 0.2  # median of lognormal(0,1) = 1
        mark_validated("lognormal", "random")

    def test_multinomial_shape_and_support(self):
        key = jax.random.PRNGKey(1)
        logits = np.log(np.array([[0.9, 0.1, 1e-9]], np.float32))
        s = _np(ops.random.multinomial(key, logits, 64))
        assert s.shape == (1, 64)
        assert set(np.unique(s)).issubset({0, 1})
        mark_validated("multinomial", "random")


class TestPreviouslyExemptOps:
    """Direct validations for ops that were only exercised indirectly via
    layer suites, so the ledger gate needs no exemption list."""

    def test_scatter_variants(self):
        ref = jnp.full((4,), 10.0)
        idx = jnp.array([0, 2])
        upd = jnp.array([3.0, 5.0])
        np.testing.assert_allclose(_np(ops.shape.scatterSub(ref, idx, upd)),
                                   [7, 10, 5, 10])
        np.testing.assert_allclose(_np(ops.shape.scatterMax(ref, idx, jnp.array([99.0, 1.0]))),
                                   [99, 10, 10, 10])
        np.testing.assert_allclose(_np(ops.shape.scatterMin(ref, idx, jnp.array([99.0, 1.0]))),
                                   [10, 10, 1, 10])
        np.testing.assert_allclose(_np(ops.shape.scatterUpdate(ref, idx, upd)),
                                   [3, 10, 5, 10])
        for k in ["scatterSub", "scatterMax", "scatterMin", "scatterUpdate"]:
            mark_validated(k, "shape")

    def test_cropping_and_padding_2d(self):
        x = jnp.asarray(np.arange(16.0, dtype=np.float32).reshape(1, 1, 4, 4))
        c = _np(ops.cnn.cropping2d(x, ((1, 1), (1, 1))))
        np.testing.assert_allclose(c[0, 0], [[5, 6], [9, 10]])
        p = _np(ops.cnn.zeroPadding2d(x, ((1, 0), (0, 1))))
        assert p.shape == (1, 1, 5, 5) and p[0, 0, 0, 0] == 0 and p[0, 0, 1, 0] == 0
        mark_validated("cropping2d", "cnn"); mark_validated("zeroPadding2d", "cnn")

    def test_adjust_contrast_and_crop_and_resize(self):
        img = np.zeros((1, 2, 2, 1), np.float32)
        img[0, :, :, 0] = [[0.0, 1.0], [0.0, 1.0]]
        got = _np(ops.image.adjustContrast(img, 2.0))
        np.testing.assert_allclose(got[0, :, :, 0], [[-0.5, 1.5], [-0.5, 1.5]])
        big = np.arange(16.0, dtype=np.float32).reshape(1, 4, 4, 1)
        crop = _np(ops.image.cropAndResize(big, np.array([[0.0, 0.0, 1.0, 1.0]], np.float32),
                                           np.array([0]), (2, 2)))
        assert crop.shape == (1, 2, 2, 1)
        np.testing.assert_allclose(crop[0, 0, 0, 0], 0.0)
        np.testing.assert_allclose(crop[0, -1, -1, 0], 15.0)
        mark_validated("adjustContrast", "image")
        mark_validated("cropAndResize", "image")


# Ledger gate, mirroring the reference's OpValidation CI rule that fails
# when a declared op has no test. Checked statically (every ledger op name
# appears as a mark_validated target in some test source) so the gate is
# independent of pytest collection order / subsetting / xdist.
def test_ledger_fully_validated():
    import pathlib
    import re
    from test_op_coverage import LEDGER
    # Every op name must be mentioned by some test source (suites reference
    # ops by exact registry name when exercising or mark_validated-ing them).
    # The LEDGER literal itself is stripped from the corpus — otherwise the
    # gate would be vacuous (every ledger name trivially appears inside it).
    corpus = []
    for f in pathlib.Path(__file__).parent.glob("test_*.py"):
        src = f.read_text()
        src = re.sub(r"LEDGER\s*=\s*\{.*?\n\}", "", src, flags=re.S)
        corpus.append(src)
    corpus = "\n".join(corpus)
    ledger_keys = {k for keys in LEDGER.values() for k in keys}
    # word-boundary match so e.g. 'select' is NOT satisfied by 'selected',
    # nor 'nonMaxSuppression' by 'nonMaxSuppressionOverlaps'
    remaining = {k for k in ledger_keys
                 if not re.search(rf"\b{re.escape(k.split('.')[1])}\b", corpus)}
    assert not remaining, f"ledger ops with no validation test: {sorted(remaining)}"


class TestOnnxLayoutOpsDirect:
    """Direct registry-level validation for the ONNX-layout ops (the importer
    suites exercise them end-to-end; the ledger needs direct marks too)."""

    def test_lstm_gru_rnn_onnx_shapes(self):
        T, B, I, H = 4, 2, 3, 5
        x = jnp.asarray(RNG.normal(size=(T, B, I)).astype(np.float32))
        z = lambda *sh: jnp.zeros(sh, jnp.float32)
        y, h, c = ops.rnn.lstmOnnx(x, z(1, 4*H, I), z(1, 4*H, H))
        assert _np(y).shape == (T, 1, B, H) and _np(c).shape == (1, B, H)
        y, h = ops.rnn.gruOnnx(x, z(2, 3*H, I), z(2, 3*H, H),
                               direction="bidirectional")
        assert _np(y).shape == (T, 2, B, H)
        y, h = ops.rnn.rnnOnnx(x, z(1, H, I), z(1, H, H),
                               activation="Relu")
        assert _np(y).shape == (T, 1, B, H)
        for k in ["lstmOnnx", "gruOnnx", "rnnOnnx"]:
            mark_validated(k, "rnn")

    def test_element_indexing(self):
        x = np.arange(12.0, dtype=np.float32).reshape(3, 4)
        idx = np.array([[1, 0, 2, 1]])
        got = _np(ops.shape.gatherElements(x, idx, axis=0))
        np.testing.assert_allclose(got, [[4.0, 1.0, 10.0, 7.0]])
        got = _np(ops.shape.scatterElements(x, np.array([[1]]), np.array([[99.0]]),
                                            axis=1, reduction="add"))
        assert got[0, 1] == 1.0 + 99.0
        eye = _np(ops.shape.eyeLike(x))
        np.testing.assert_allclose(eye, np.eye(3, 4))
        for k in ["gatherElements", "scatterElements", "eyeLike"]:
            mark_validated(k, "shape")

    def test_activation_stragglers_and_einsum(self):
        v = np.array([-1.0, -0.2, 0.3, 0.9], np.float32)
        got = _np(ops.nn.shrink(v, bias=0.1, lambd=0.5))
        np.testing.assert_allclose(got, [-0.9, 0.0, 0.0, 0.8], rtol=1e-6)
        x = np.ones((2, 3, 4, 4), np.float32)
        mvn = _np(ops.nn.meanVarianceNormalization(x))
        np.testing.assert_allclose(mvn, 0.0)
        e = _np(ops.linalg.einsum(np.eye(2, dtype=np.float32),
                                  np.ones((2, 2), np.float32), equation="ij,jk->ik"))
        np.testing.assert_allclose(e, 1.0)
        assert float(_np(ops.loss.l2Loss(np.array([3.0, 4.0])))) == 12.5
        mark_validated("shrink", "nn")
        mark_validated("meanVarianceNormalization", "nn")
        mark_validated("einsum", "linalg")
        mark_validated("l2Loss", "loss")


class TestFinalStragglers:
    def test_bitcast_and_hash(self):
        got = _np(ops.math.bitcast(np.float32(1.0), jnp.int32))
        assert got == 0x3F800000
        h1 = int(_np(ops.math.hashCode(np.array([1.0, 2.0], np.float32))))
        h2 = int(_np(ops.math.hashCode(np.array([2.0, 1.0], np.float32))))
        assert h1 != h2  # order-sensitive
        mark_validated("bitcast", "math"); mark_validated("hashCode", "math")

    def test_assert_and_where_nonzero(self):
        assert bool(_np(ops.math.assertOp(np.array([True, True]))))
        with pytest.raises(AssertionError, match="boom"):
            ops.math.assertOp(np.array([True, False]), message="boom")
        idx = _np(ops.shape.whereNonzero(np.array([[0, 3], [5, 0]])))
        np.testing.assert_array_equal(idx, [[0, 1], [1, 0]])
        mark_validated("assertOp", "math")
        mark_validated("whereNonzero", "shape")

    def test_fake_quant(self):
        x = np.array([-0.3, 0.0, 0.4, 1.7], np.float32)
        q = _np(ops.math.fakeQuantWithMinMaxVars(x, 0.0, 1.0, num_bits=8))
        assert q[0] == 0.0 and q[3] == pytest.approx(1.0, abs=1e-2)
        assert abs(q[2] - 0.4) < 1.0 / 255 + 1e-6  # quantized to the grid
        xc = np.stack([x, x], axis=-1)
        qc = _np(ops.math.fakeQuantWithMinMaxVarsPerChannel(
            xc, np.array([0.0, -1.0]), np.array([1.0, 1.0])))
        assert qc.shape == xc.shape and qc[0, 1] == pytest.approx(-0.3, abs=1e-2)
        mark_validated("fakeQuantWithMinMaxVars", "math")
        mark_validated("fakeQuantWithMinMaxVarsPerChannel", "math")

    def test_knn_and_match_condition(self):
        d = float(_np(ops.math.knnMindistance(
            np.array([3.0, 0.0]), np.array([0.0, 0.0]), np.array([1.0, 1.0]))))
        assert d == pytest.approx(2.0)
        m = _np(ops.math.matchConditionTransform(np.array([1.0, 5.0, 3.0]),
                                                 3.0, condition="gte"))
        np.testing.assert_array_equal(m, [False, True, True])
        mark_validated("knnMindistance", "math")
        mark_validated("matchConditionTransform", "math")

    def test_yiq_roundtrip(self):
        rgb = np.abs(RNG.normal(size=(2, 2, 3))).astype(np.float32)
        yiq = ops.image.rgbToYiq(rgb)
        back = _np(ops.image.yiqToRgb(_np(yiq)))
        np.testing.assert_allclose(back, rgb, atol=1e-5)
        mark_validated("rgbToYiq", "image"); mark_validated("yiqToRgb", "image")

    def test_compare_and_bitpack(self):
        x = np.array([1, 0, 0, 0, 0, 0, 0, 1], np.float32)
        got = _np(ops.math.compareAndBitpack(x, 0.5))
        assert got[0] == 0b10000001
        mark_validated("compareAndBitpack", "math")

    def test_ctc_greedy_decoder(self):
        # frames argmax: [1,1,0,2,2] -> collapse repeats, drop blanks: [1,2]
        lp = np.full((1, 5, 3), -10.0, np.float32)
        for t, s in enumerate([1, 1, 0, 2, 2]):
            lp[0, t, s] = 0.0
        seq, lens = ops.loss.ctcGreedyDecoder(lp, np.array([5]))
        assert int(_np(lens)[0]) == 2
        np.testing.assert_array_equal(_np(seq)[0, :2], [1, 2])
        mark_validated("ctcGreedyDecoder", "loss")

    def test_log_poisson_loss(self):
        t = np.array([2.0], np.float32)
        li = np.array([0.5], np.float32)
        got = float(_np(ops.loss.logPoissonLoss(t, li)))
        assert got == pytest.approx(np.exp(0.5) - 2 * 0.5, rel=1e-6)
        mark_validated("logPoissonLoss", "loss")

    def test_fake_quant_rejects_degenerate_range(self):
        with pytest.raises(ValueError, match="min_val < max_val"):
            ops.math.fakeQuantWithMinMaxVars(np.ones(4, np.float32), 0.0, 0.0)

    def test_hash_code_config_independent_recurrence(self):
        # h = 31*h + e over the RAW bytes, masked to 32 bits:
        # float32 1.0 = 00 00 80 3f (LE) -> ((0*31+0)*31+128)*31+63
        assert int(_np(ops.math.hashCode(np.array([1.0], np.float32)))) \
            == 128 * 31 + 63
        # dtype-sensitive: int64 values that collide under a float32 cast
        # must hash differently (hash is over native bytes)
        a = ops.math.hashCode(np.array([16777216], np.int64))
        b = ops.math.hashCode(np.array([16777217], np.int64))
        assert int(_np(a)) != int(_np(b))
        # vectorized path handles large inputs fast
        big = np.arange(1_000_000, dtype=np.float32)
        assert np.isfinite(float(_np(ops.math.hashCode(big))))
