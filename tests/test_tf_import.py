"""TF frozen-graph import corpus (ref: TFGraphTestAllSameDiff — frozen graphs
executed both by TF and by the imported SameDiff, outputs compared). Graphs are
generated in-process with tf.function freezing instead of stored fixtures."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tensorflow import TensorflowFrameworkImporter  # noqa: E402

RNG = np.random.default_rng(0)


def _freeze(fn, *specs):
    """Concrete tf.function -> frozen GraphDef + input/output names."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    cf = tf.function(fn).get_concrete_function(*specs)
    # keep functional While/If nodes (+ library) — the importer maps them to
    # structured lax control flow; v1-style Enter/Switch dataflow is not jittable
    frozen = convert_variables_to_constants_v2(cf, lower_control_flow=False)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd, in_names, out_names, frozen


def _run_parity(fn, inputs, atol=1e-5):
    specs = [tf.TensorSpec(x.shape, tf.as_dtype(x.dtype)) for x in inputs]
    gd, in_names, out_names, frozen = _freeze(fn, *specs)
    expected = frozen(*[tf.constant(x) for x in inputs])
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    sd = TensorflowFrameworkImporter.runImport(gd)
    phs = dict(zip(in_names, inputs))
    for out_name, exp in zip(out_names, expected):
        got = sd.getVariable(out_name).eval(phs).toNumpy()
        np.testing.assert_allclose(got, np.asarray(exp), atol=atol)
    return sd


def test_mlp_graph():
    w1 = RNG.normal(size=(6, 16)).astype(np.float32)
    b1 = RNG.normal(size=(16,)).astype(np.float32)
    w2 = RNG.normal(size=(16, 3)).astype(np.float32)

    def f(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.softmax(tf.matmul(h, w2))

    _run_parity(f, [RNG.normal(size=(4, 6)).astype(np.float32)])


def test_conv_pool_graph():
    k = RNG.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.1

    def f(x):  # NHWC
        y = tf.nn.conv2d(x, k, strides=1, padding="SAME")
        y = tf.nn.relu(y)
        y = tf.nn.max_pool2d(y, 2, 2, padding="VALID")
        return tf.reduce_mean(y, axis=[1, 2])

    _run_parity(f, [RNG.normal(size=(2, 8, 8, 2)).astype(np.float32)], atol=1e-4)


def test_attention_block_graph():
    """Scaled-dot-product attention — the BERT core pattern."""
    D, H = 16, 4
    wq = RNG.normal(size=(D, D)).astype(np.float32) * 0.1
    wk = RNG.normal(size=(D, D)).astype(np.float32) * 0.1
    wv = RNG.normal(size=(D, D)).astype(np.float32) * 0.1

    def f(x):  # (B, T, D)
        B, T = tf.shape(x)[0], tf.shape(x)[1]
        q = tf.matmul(x, tf.reshape(wq, (1, D, D)) + tf.zeros((1, 1, 1)))
        k = tf.matmul(x, tf.reshape(wk, (1, D, D)) + tf.zeros((1, 1, 1)))
        v = tf.matmul(x, tf.reshape(wv, (1, D, D)) + tf.zeros((1, 1, 1)))
        s = tf.matmul(q, k, transpose_b=True) / tf.sqrt(tf.cast(D, tf.float32))
        p = tf.nn.softmax(s, axis=-1)
        return tf.matmul(p, v)

    _run_parity(f, [RNG.normal(size=(2, 6, D)).astype(np.float32)], atol=1e-4)


def test_layernorm_composite_graph():
    """LayerNorm built from primitives (mean/sub/square/rsqrt) — exercises
    reduce + broadcast chains."""
    gamma = RNG.normal(size=(8,)).astype(np.float32)
    beta = RNG.normal(size=(8,)).astype(np.float32)

    def f(x):
        mu = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * tf.math.rsqrt(var + 1e-6) * gamma + beta

    _run_parity(f, [RNG.normal(size=(3, 5, 8)).astype(np.float32)], atol=1e-5)


def test_shape_ops_graph():
    def f(x):
        y = tf.transpose(x, (0, 2, 1))
        y = tf.reshape(y, (-1, 6))
        y = tf.concat([y, y], axis=1)
        y = tf.expand_dims(y, 1)
        return tf.squeeze(y, axis=1)

    _run_parity(f, [RNG.normal(size=(2, 6, 3)).astype(np.float32)])


def test_embedding_gather_graph():
    table = RNG.normal(size=(11, 5)).astype(np.float32)

    def f(ids):
        e = tf.gather(table, ids)
        return tf.reduce_sum(e, axis=1)

    _run_parity(f, [RNG.integers(0, 11, (3, 7)).astype(np.int32)])


def test_strided_slice_graph():
    def f(x):
        return x[:, 1:4, ::2]

    _run_parity(f, [RNG.normal(size=(2, 6, 8)).astype(np.float32)])


def test_unknown_op_reports_clearly():
    gd, *_ = _freeze(lambda x: tf.raw_ops.Betainc(a=x, b=x, x=x),
                     tf.TensorSpec((2,), tf.float32))
    with pytest.raises(ValueError, match="no mapping rule"):
        TensorflowFrameworkImporter.runImport(gd)


def test_argmax_and_dilated_conv_graph():
    k = RNG.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.1

    def f(x):
        y = tf.nn.conv2d(x, k, strides=1, padding="SAME", dilations=[1, 2, 2, 1])
        return tf.argmax(tf.reduce_mean(y, axis=[1, 2]), axis=1)

    _run_parity(f, [RNG.normal(size=(2, 8, 8, 2)).astype(np.float32)], atol=1e-4)


def test_while_loop_graph():
    """tf.while_loop freezes to a functional While node whose cond/body live
    in the graph's function library — imported as a structured lax loop."""
    def f(x):
        i = tf.constant(0)
        c = lambda i, acc: i < 4
        b = lambda i, acc: (i + 1, acc * 2.0)
        _, out = tf.while_loop(c, b, (i, x))
        return out

    _run_parity(f, [RNG.normal(size=(3,)).astype(np.float32)])


def test_cond_graph():
    """tf.cond freezes to StatelessIf with then/else function-library branches."""
    def f(x):
        return tf.cond(tf.reduce_sum(x) > 0.0,
                       lambda: x * 2.0, lambda: x - 1.0)

    _run_parity(f, [np.array([1.0, 2.0], np.float32)])
    _run_parity(f, [np.array([-5.0, 2.0], np.float32)])


def test_split_and_dynamic_reshape_graph():
    def f(x):
        a, b = tf.split(x, 2, axis=-1)
        B = tf.shape(x)[0]
        return tf.reshape(a * b, (B, -1))

    _run_parity(f, [RNG.normal(size=(4, 8)).astype(np.float32)])


def test_bert_base_architecture_import_parity():
    """BASELINE config #4's import path at architecture fidelity: a BERT-style
    encoder (frozen GraphDef, same op mix as BERT-base: Gather embeddings,
    moments layernorm, BatchMatMulV2 attention, erf-GELU) imports and matches
    live TF. Full-size import is exercised in tools/bench_tf_import.py."""
    from tools.tf_bert import build_frozen_bert
    gd, i, o, frozen = build_frozen_bert(L=2, H=64, A=4, V=100, T=16,
                                         intermediate=128)
    sd = TensorflowFrameworkImporter.runImport(gd)
    ids = RNG.integers(0, 100, (2, 16)).astype(np.int32)
    got = sd.getVariable(o).eval({i: ids}).toNumpy()
    exp = frozen(tf.constant(ids))
    if isinstance(exp, (list, tuple)):
        exp = exp[0]
    np.testing.assert_allclose(got, np.asarray(exp), atol=1e-4)


def test_bert_import_finetune_loss_decreases():
    """Fine-tune THROUGH the imported graph (ref: SameDiff BERT fine-tune,
    SURVEY §3.3): constants -> variables, new head, whole-graph jitted fit."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train import Adam
    from tools.tf_bert import build_frozen_bert

    gd, i, o, _ = build_frozen_bert(L=2, H=64, A=4, V=100, T=16,
                                    intermediate=128)
    sd = TensorflowFrameworkImporter.runImport(gd)
    assert sd.convertAllConstantsToVariables() > 0
    pooled = sd.reduce.mean(sd.getVariable(o), dims=(1,))
    W = sd.var("cls_W", (64, 4), weightInit="XAVIER")
    logits = sd.linalg.matmul(pooled, W)
    labels = sd.placeHolder("labels", shape=(8,), dtype=jnp.int32)
    loss = sd.loss.sparseMcxent(labels, logits)
    sd.setLossVariables(loss.name)
    sd.setTrainingConfig(TrainingConfig(updater=Adam(1e-3)))
    ids = RNG.integers(0, 100, (8, 16)).astype(np.int32)
    y = RNG.integers(0, 4, (8,)).astype(np.int32)
    hist = []
    for _ in range(12):
        hist += sd.fit({i: ids, "labels": y})
    assert hist[-1] < hist[0] * 0.7, (hist[0], hist[-1])


# ---- round-2 widened rule set (Tile/Range/Slice/Cumsum/TopK/Einsum/...) ----


def test_shape_manipulation_ops():
    def f(x):
        t = tf.tile(x, [2, 1])
        r = tf.reverse(t, axis=[1])
        s = tf.slice(r, [1, 0], [3, -1])
        return tf.unstack(s, axis=0)[1]

    _run_parity(f, [RNG.normal(size=(3, 5)).astype(np.float32)])


def test_range_and_cumsum_variants():
    def f(x):
        idx = tf.range(0.0, 4.0, 1.0)
        c0 = tf.cumsum(x, axis=1)
        c1 = tf.cumsum(x, axis=1, exclusive=True)
        c2 = tf.cumsum(x, axis=1, reverse=True)
        return c0 + c1 + c2 + idx

    _run_parity(f, [RNG.normal(size=(2, 4)).astype(np.float32)])


def test_topk_and_gather_nd():
    def f(x):
        vals, idx = tf.math.top_k(x, k=2)
        g = tf.gather_nd(x, [[0, 1], [1, 0]])
        return vals + tf.cast(idx, tf.float32)[:, :1] + g[0]

    _run_parity(f, [RNG.normal(size=(3, 5)).astype(np.float32)])


def test_scatter_nd_and_clip():
    def f(x):
        s = tf.scatter_nd([[0], [2]], [5.0, 7.0], [4])
        return tf.clip_by_value(x + s, -1.0, 1.0)

    _run_parity(f, [RNG.normal(size=(4,)).astype(np.float32)])


def test_mirror_pad_and_l2loss():
    def f(x):
        p = tf.pad(x, [[1, 1], [0, 0]], mode="REFLECT")
        return p + tf.nn.l2_loss(x)

    _run_parity(f, [RNG.normal(size=(3, 4)).astype(np.float32)])


def test_space_batch_and_depth_ops():
    def f(x):  # NHWC
        y = tf.space_to_batch(x, [2, 2], [[0, 0], [0, 0]])
        y = tf.batch_to_space(y, [2, 2], [[0, 0], [0, 0]])
        d = tf.nn.space_to_depth(x, 2)
        d = tf.nn.depth_to_space(d, 2)
        return y + d

    _run_parity(f, [RNG.normal(size=(1, 4, 4, 3)).astype(np.float32)])


def test_resize_ops():
    def f(x):  # NHWC
        a = tf.image.resize(x, [6, 6], method="bilinear")
        b = tf.image.resize(x, [6, 6], method="nearest")
        return a + b

    _run_parity(f, [RNG.normal(size=(1, 3, 3, 2)).astype(np.float32)], atol=1e-4)


def test_einsum_and_lrn():
    def f(x, y):
        e = tf.einsum("bij,bjk->bik", x, y)
        return e

    _run_parity(f, [RNG.normal(size=(2, 3, 4)).astype(np.float32),
                    RNG.normal(size=(2, 4, 5)).astype(np.float32)])

    def g(x):  # NHWC LRN
        return tf.nn.local_response_normalization(
            x, depth_radius=2, bias=1.0, alpha=0.5, beta=0.5)

    _run_parity(g, [np.abs(RNG.normal(size=(1, 3, 3, 8))).astype(np.float32)],
                atol=1e-4)


def test_extra_unary_ops():
    def f(x):
        return (tf.math.sinh(x) + tf.math.cosh(x) + tf.math.expm1(x)
                + tf.math.erfc(x) + tf.math.atan(x))

    _run_parity(f, [RNG.normal(size=(8,)).astype(np.float32) * 0.5], atol=1e-4)


def test_tf1_resize_coordinate_modes():
    """align_corners / legacy (neither) coordinate rules must match the TF
    kernels exactly — TF2's half-pixel default is a different sampling."""
    x = RNG.normal(size=(1, 4, 5, 2)).astype(np.float32)

    def ac_bilinear(x):
        return tf.compat.v1.image.resize_bilinear(x, [7, 9], align_corners=True)

    def legacy_bilinear(x):
        return tf.compat.v1.image.resize_bilinear(x, [7, 9],
                                                  align_corners=False)

    def ac_nearest(x):
        return tf.compat.v1.image.resize_nearest_neighbor(x, [7, 9],
                                                          align_corners=True)

    def legacy_nearest(x):
        return tf.compat.v1.image.resize_nearest_neighbor(x, [7, 9],
                                                          align_corners=False)

    for fn in (ac_bilinear, legacy_bilinear, ac_nearest, legacy_nearest):
        _run_parity(fn, [x], atol=1e-5)
