"""Op registry + namespace tests (ref: OpValidation / LayerOpValidation /
ReductionOpValidation suites in nd4j). Validated ops get marked in the
coverage ledger."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import nd, ops
from deeplearning4j_tpu.ops import mark_validated


def check(namespace, name, out, expected, atol=1e-5):
    np.testing.assert_allclose(np.asarray(out.toNumpy(), dtype=np.float64),
                               expected, atol=atol)
    mark_validated(name, namespace)


class TestMathOps:
    def test_transforms(self):
        x = nd.create([0.5, 1.0, 2.0])
        check("math", "exp", ops.math.exp(x), np.exp([0.5, 1, 2]))
        check("math", "log", ops.math.log(x), np.log([0.5, 1, 2]))
        check("math", "sqrt", ops.math.sqrt(x), np.sqrt([0.5, 1, 2]))
        check("math", "tanh", ops.math.tanh(x), np.tanh([0.5, 1, 2]))
        check("math", "abs", ops.math.abs(nd.create([-1.0, 2.0])), [1, 2])
        check("math", "sign", ops.math.sign(nd.create([-3.0, 0.0, 9.0])), [-1, 0, 1])
        check("math", "square", ops.math.square(x), [0.25, 1, 4])
        check("math", "floor", ops.math.floor(nd.create([1.7])), [1.0])
        check("math", "erf", ops.math.erf(nd.create([0.0])), [0.0])

    def test_binary(self):
        a, b = nd.create([1.0, 4.0]), nd.create([3.0, 2.0])
        check("math", "max", ops.math.max(a, b), [3, 4])
        check("math", "min", ops.math.min(a, b), [1, 2])
        check("math", "pow", ops.math.pow(a, 2.0), [1, 16])
        check("math", "clipByValue", ops.math.clipByValue(nd.create([-5.0, 0.5, 5.0]), -1.0, 1.0), [-1, 0.5, 1])

    def test_clip_by_norm(self):
        x = nd.create([3.0, 4.0])
        check("math", "clipByNorm", ops.math.clipByNorm(x, 1.0), [0.6, 0.8])


class TestReduceOps:
    def test_basic(self):
        x = nd.create([[1.0, 2.0], [3.0, 4.0]])
        check("reduce", "sum", ops.reduce.sum(x), 10.0)
        check("reduce", "mean", ops.reduce.mean(x, 0), [2, 3])
        check("reduce", "max", ops.reduce.max(x, 1), [2, 4])
        check("reduce", "norm2", ops.reduce.norm2(nd.create([3.0, 4.0])), 5.0)
        check("reduce", "logSumExp", ops.reduce.logSumExp(nd.create([0.0, 0.0])), np.log(2))

    def test_distances(self):
        a, b = nd.create([1.0, 0.0]), nd.create([0.0, 1.0])
        check("reduce", "euclideanDistance", ops.reduce.euclideanDistance(a, b), np.sqrt(2))
        check("reduce", "manhattanDistance", ops.reduce.manhattanDistance(a, b), 2.0)
        check("reduce", "cosineSimilarity", ops.reduce.cosineSimilarity(a, b), 0.0)

    def test_argmax(self):
        check("reduce", "argmax", ops.reduce.argmax(nd.create([[1.0, 9.0], [8.0, 2.0]]), 1), [1, 0])


class TestShapeOps:
    def test_manipulation(self):
        x = nd.arange(6).reshape(2, 3)
        assert ops.shape.transpose(x).shape == (3, 2)
        assert ops.shape.expandDims(x, 0).shape == (1, 2, 3)
        assert ops.shape.tile(x, (2, 1)).shape == (4, 3)
        mark_validated("transpose", "shape")
        mark_validated("expandDims", "shape")
        mark_validated("tile", "shape")

    def test_gather_scatter(self):
        x = nd.create([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        check("shape", "gather", ops.shape.gather(x, nd.create([0, 2], dtype="INT")), [[1, 2], [5, 6]])
        z = nd.zeros(3, 2)
        out = ops.shape.scatterAdd(z, nd.create([1], dtype="INT"), nd.create([[9.0, 9.0]]))
        check("shape", "scatterAdd", out, [[0, 0], [9, 9], [0, 0]])

    def test_one_hot_where(self):
        check("shape", "oneHot", ops.shape.oneHot(nd.create([0, 2], dtype="INT"), 3),
              [[1, 0, 0], [0, 0, 1]])
        check("shape", "where", ops.shape.where(nd.create([True, False]), nd.create([1.0, 1.0]),
                                                nd.create([2.0, 2.0])), [1, 2])

    def test_segment_sum(self):
        data = nd.create([1.0, 2.0, 3.0, 4.0])
        seg = nd.create([0, 0, 1, 1], dtype="INT")
        check("shape", "segmentSum", ops.shape.segmentSum(data, seg, 2), [3, 7])

    def test_sequence_mask(self):
        check("shape", "sequenceMask", ops.shape.sequenceMask(nd.create([1, 3], dtype="INT"), 4),
              [[1, 0, 0, 0], [1, 1, 1, 0]])


class TestLinalgOps:
    def test_matmul_inverse(self):
        a = nd.create([[2.0, 0.0], [0.0, 4.0]])
        check("linalg", "inverse", ops.linalg.inverse(a), [[0.5, 0], [0, 0.25]])
        check("linalg", "det", ops.linalg.det(a), 8.0)
        check("linalg", "trace", ops.linalg.trace(a), 6.0)
        b = nd.create([[1.0], [2.0]])
        check("linalg", "solve", ops.linalg.solve(a, b), [[0.5], [0.5]])
        mark_validated("matmul", "linalg")

    def test_cholesky(self):
        a = nd.create([[4.0, 0.0], [0.0, 9.0]])
        check("linalg", "cholesky", ops.linalg.cholesky(a), [[2, 0], [0, 3]])


class TestNNOps:
    def test_activations(self):
        x = nd.create([-1.0, 0.0, 2.0])
        check("nn", "relu", ops.nn.relu(x), [0, 0, 2])
        check("nn", "sigmoid", ops.nn.sigmoid(nd.create([0.0])), [0.5])
        check("nn", "leakyRelu", ops.nn.leakyRelu(x, 0.1), [-0.1, 0, 2])
        check("nn", "elu", ops.nn.elu(nd.create([0.0, 1.0])), [0, 1])
        sm = ops.nn.softmax(nd.create([[1.0, 1.0]]))
        check("nn", "softmax", sm, [[0.5, 0.5]])
        check("nn", "softplus", ops.nn.softplus(nd.create([0.0])), [np.log(2)])
        check("nn", "hardTanh", ops.nn.hardTanh(nd.create([-5.0, 0.3, 5.0])), [-1, 0.3, 1])

    def test_layer_norm(self):
        x = nd.create([[1.0, 2.0, 3.0]])
        out = ops.nn.layerNorm(x)
        np.testing.assert_allclose(out.toNumpy().mean(), 0.0, atol=1e-5)
        mark_validated("layerNorm", "nn")

    def test_batch_norm(self):
        x = nd.ones(2, 3, 2, 2)
        mean, var = nd.zeros(3), nd.ones(3)
        out = ops.nn.batchNorm(x, mean, var, eps=0.0)
        np.testing.assert_allclose(out.toNumpy(), np.ones((2, 3, 2, 2)), atol=1e-5)
        mark_validated("batchNorm", "nn")

    def test_attention(self):
        q = nd.rand(2, 4, 8)
        out = ops.nn.dotProductAttention(q, q, q)
        assert out.shape == (2, 4, 8)
        mark_validated("dotProductAttention", "nn")

    def test_mha_shapes(self):
        B, T, D, H = 2, 5, 16, 4
        x = nd.rand(B, T, D)
        w = [nd.randn(D, D).mul(0.1) for _ in range(4)]
        out = ops.nn.multiHeadDotProductAttention(x, x, *w, num_heads=H)
        assert out.shape == (B, T, D)
        mark_validated("multiHeadDotProductAttention", "nn")

    def test_embedding(self):
        table = nd.create([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        check("nn", "embeddingLookup", ops.nn.embeddingLookup(table, nd.create([2, 0], dtype="INT")),
              [[3, 3], [1, 1]])


class TestCNNOps:
    def test_conv2d_identity(self):
        x = nd.rand(1, 1, 5, 5)
        w = nd.zeros(1, 1, 3, 3)
        w.putScalar((0, 0, 1, 1), 1.0)  # identity kernel
        out = ops.cnn.conv2d(x, w, padding="SAME")
        np.testing.assert_allclose(out.toNumpy(), x.toNumpy(), atol=1e-6)
        mark_validated("conv2d", "cnn")

    def test_conv2d_shapes(self):
        x = nd.rand(2, 3, 8, 8)
        w = nd.randn(16, 3, 3, 3)
        assert ops.cnn.conv2d(x, w, padding="SAME").shape == (2, 16, 8, 8)
        assert ops.cnn.conv2d(x, w, padding="VALID").shape == (2, 16, 6, 6)
        assert ops.cnn.conv2d(x, w, strides=(2, 2), padding="SAME").shape == (2, 16, 4, 4)

    def test_pooling(self):
        x = nd.create(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = ops.cnn.maxPool2d(x, (2, 2))
        check("cnn", "maxPool2d", mp, [[[[5, 7], [13, 15]]]])
        ap = ops.cnn.avgPool2d(x, (2, 2))
        check("cnn", "avgPool2d", ap, [[[[2.5, 4.5], [10.5, 12.5]]]])

    def test_depthwise(self):
        x = nd.rand(1, 3, 6, 6)
        w = nd.randn(3, 1, 3, 3)
        assert ops.cnn.depthwiseConv2d(x, w, padding="SAME").shape == (1, 3, 6, 6)
        mark_validated("depthwiseConv2d", "cnn")

    def test_upsampling_space_depth(self):
        x = nd.rand(1, 4, 2, 2)
        assert ops.cnn.upsampling2d(x, (2, 2)).shape == (1, 4, 4, 4)
        s2d = ops.cnn.spaceToDepth(nd.rand(1, 1, 4, 4), 2)
        assert s2d.shape == (1, 4, 2, 2)
        d2s = ops.cnn.depthToSpace(s2d, 2)
        assert d2s.shape == (1, 1, 4, 4)
        mark_validated("upsampling2d", "cnn")
        mark_validated("spaceToDepth", "cnn")
        mark_validated("depthToSpace", "cnn")

    def test_global_pool(self):
        x = nd.ones(2, 3, 4, 4)
        check("cnn", "globalAvgPool", ops.cnn.globalAvgPool(x), np.ones((2, 3)))


class TestRNNOps:
    def test_lstm_layer_shapes(self):
        B, T, I, H = 2, 5, 3, 4
        x = nd.rand(B, T, I)
        h0, c0 = nd.zeros(B, H), nd.zeros(B, H)
        w_ih, w_hh, b = nd.randn(I, 4 * H).mul(0.1), nd.randn(H, 4 * H).mul(0.1), nd.zeros(4 * H)
        ys, (hT, cT) = ops.rnn.lstmLayer(x, h0, c0, w_ih, w_hh, b)
        assert ys.shape == (B, T, H)
        assert hT.shape == (B, H)
        np.testing.assert_allclose(ys.toNumpy()[:, -1], hT.toNumpy(), atol=1e-6)
        mark_validated("lstmLayer", "rnn")
        mark_validated("lstmCell", "rnn")

    def test_lstm_mask_freezes_state(self):
        B, T, I, H = 1, 4, 2, 3
        x = nd.rand(B, T, I)
        mask = nd.create([[1.0, 1.0, 0.0, 0.0]])
        h0, c0 = nd.zeros(B, H), nd.zeros(B, H)
        w_ih, w_hh, b = nd.randn(I, 4 * H), nd.randn(H, 4 * H), nd.zeros(4 * H)
        ys, (hT, _) = ops.rnn.lstmLayer(x, h0, c0, w_ih, w_hh, b, mask=mask)
        np.testing.assert_allclose(ys.toNumpy()[0, 1], hT.toNumpy()[0], atol=1e-6)

    def test_gru_simple_rnn(self):
        B, T, I, H = 2, 3, 4, 5
        x = nd.rand(B, T, I)
        h0 = nd.zeros(B, H)
        ys, hT = ops.rnn.gru(x, h0, nd.randn(I, 3 * H), nd.randn(H, 3 * H), nd.zeros(3 * H), nd.zeros(3 * H))
        assert ys.shape == (B, T, H)
        mark_validated("gru", "rnn")
        ys2, hT2 = ops.rnn.simpleRnn(x, h0, nd.randn(I, H), nd.randn(H, H), nd.zeros(H))
        assert ys2.shape == (B, T, H)
        mark_validated("simpleRnn", "rnn")


class TestLossOps:
    def test_mse(self):
        l, p = nd.create([[1.0, 2.0]]), nd.create([[1.5, 2.5]])
        check("loss", "mse", ops.loss.mse(l, p), 0.25)

    def test_mcxent(self):
        labels = nd.create([[1.0, 0.0]])
        probs = nd.create([[0.8, 0.2]])
        check("loss", "mcxent", ops.loss.mcxent(labels, probs), -np.log(0.8))
        logits = nd.create([[2.0, 0.0]])
        expected = -np.log(np.exp(2) / (np.exp(2) + 1))
        check("loss", "mcxent", ops.loss.mcxent(labels, logits, from_logits=True), expected)

    def test_sparse_mcxent(self):
        logits = nd.create([[2.0, 0.0], [0.0, 2.0]])
        labels = nd.create([0, 1], dtype="INT")
        expected = -np.log(np.exp(2) / (np.exp(2) + 1))
        check("loss", "sparseMcxent", ops.loss.sparseMcxent(labels, logits), expected)

    def test_binary_xent_hinge_huber(self):
        l, p = nd.create([[1.0]]), nd.create([[0.9]])
        check("loss", "binaryXent", ops.loss.binaryXent(l, p), -np.log(0.9))
        check("loss", "hinge", ops.loss.hinge(nd.create([[1.0]]), nd.create([[0.5]])), 0.5)
        check("loss", "huber", ops.loss.huber(nd.create([[0.0]]), nd.create([[2.0]])), 1.5)

    def test_losses_differentiable(self):
        import jax

        def f(p):
            return ops.loss.mse(nd.create([[1.0, 2.0]]), NDArrayFrom(p)).jax

        # raw jnp path: losses must be differentiable for training
        from deeplearning4j_tpu.ops import get
        fn = get("mse", "loss").fn
        g = jax.grad(lambda p: fn(jnp.array([[1.0, 2.0]]), p))(jnp.array([[1.5, 2.5]]))
        np.testing.assert_allclose(np.asarray(g), [[0.5, 0.5]])


def NDArrayFrom(p):
    from deeplearning4j_tpu import NDArray
    return NDArray(p)


class TestImageOps:
    def test_resize(self):
        x = nd.rand(1, 3, 4, 4)
        assert ops.image.resizeBilinear(x, (8, 8)).shape == (1, 3, 8, 8)
        assert ops.image.resizeNearest(x, (2, 2)).shape == (1, 3, 2, 2)
        mark_validated("resizeBilinear", "image")
        mark_validated("resizeNearest", "image")

    def test_rgb_to_gray(self):
        x = nd.ones(1, 2, 2, 3)
        out = ops.image.rgbToGrayscale(x)
        np.testing.assert_allclose(out.toNumpy(), np.full((1, 2, 2, 1), 0.9999), atol=1e-3)
        mark_validated("rgbToGrayscale", "image")

    def test_nms(self):
        boxes = nd.create([[0, 0, 1, 1], [0, 0, 1, 1], [2, 2, 3, 3]], dtype="FLOAT")
        scores = nd.create([0.9, 0.8, 0.7])
        sel = ops.image.nonMaxSuppression(boxes, scores, 2)
        assert sel.toNumpy().tolist() == [0, 2]
        mark_validated("nonMaxSuppression", "image")


class TestRandomOps:
    def test_key_explicit(self):
        key = jax.random.key(0)
        u = ops.random.uniform(key, (100,))
        assert 0.0 <= float(u.minNumber()) and float(u.maxNumber()) <= 1.0
        mark_validated("uniform", "random")
        d = ops.random.dropout(key, nd.ones(1000), 0.5)
        kept = float((d.toNumpy() > 0).mean())
        assert 0.35 < kept < 0.65
        mark_validated("dropout", "random")


class TestCoverageLedger:
    def test_report_runs(self):
        from deeplearning4j_tpu.ops import coverage_report
        done, todo = coverage_report()
        assert len(done) + len(todo) == len(__import__("deeplearning4j_tpu.ops", fromlist=["REGISTRY"]).REGISTRY)
