"""Multi-host input sharding (round-5 verdict #6; ref: the reference's Spark
executors each training on their own RDD partition via rdd.mapPartitions,
SURVEY.md §3.5). Unit tests on the wrappers, plus a REAL 2-process
jax.distributed run where each process reads a DISJOINT shard via the
public shard() API and the result matches a single-host golden."""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data import (
    DataSet, ListDataSetIterator, ShardSpec, ShardedDataSetIterator,
    ShardedInputSplit, shard)
from deeplearning4j_tpu.datavec.split import CollectionInputSplit, FileSplit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stream(n, b=4, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(b, 6)).astype(np.float32),
                    rng.normal(size=(b, 2)).astype(np.float32))
            for _ in range(n)]


class TestShardSpec:
    def test_validation(self):
        with pytest.raises(ValueError):
            ShardSpec(2, 2)
        with pytest.raises(ValueError):
            ShardSpec(-1, 2)

    def test_current_single_process(self):
        spec = ShardSpec.current()
        assert (spec.index, spec.count) == (0, 1)


class TestShardedInputSplit:
    def test_disjoint_and_covering(self):
        base = CollectionInputSplit([f"f{i}" for i in range(10)])
        shards = [ShardedInputSplit(base, ShardSpec(i, 3)).locations()
                  for i in range(3)]
        assert [len(s) for s in shards] == [4, 3, 3]  # balanced within 1
        seen = [p for s in shards for p in s]
        assert sorted(seen) == sorted(base.locations())
        assert len(set(seen)) == 10  # disjoint

    def test_file_split_deterministic_order(self, tmp_path):
        for i in range(6):
            (tmp_path / f"r{i}.csv").write_text("x")
        base = FileSplit(str(tmp_path), allowFormats=[".csv"])
        a = ShardedInputSplit(base, ShardSpec(0, 2)).locations()
        b = ShardedInputSplit(base, ShardSpec(1, 2)).locations()
        assert len(a) == len(b) == 3 and not set(a) & set(b)

    def test_shard_dispatch(self):
        base = CollectionInputSplit(["a", "b", "c"])
        assert isinstance(shard(base, 0, 2), ShardedInputSplit)
        assert shard(base, 1, 2).locations() == ["b"]
        with pytest.raises(TypeError):
            shard(42, 0, 2)


class TestShardedDataSetIterator:
    def test_round_robin_assignment_drops_partial_round(self):
        """7 batches / 2 shards: the incomplete final round (batch 6) is
        dropped by BOTH shards — every shard steps exactly 3 times, so a
        lockstep collective per step cannot hang on an uneven tail."""
        data = _stream(7)
        got = {i: list(shard(ListDataSetIterator(data), i, 2)) for i in range(2)}
        assert [d.features.tolist() for d in got[0]] == \
            [data[j].features.tolist() for j in (0, 2, 4)]
        assert [d.features.tolist() for d in got[1]] == \
            [data[j].features.tolist() for j in (1, 3, 5)]
        assert len(got[0]) == len(got[1]) == 3

    def test_keep_partial_round_option(self):
        data = _stream(7)
        a = list(ShardedDataSetIterator(ListDataSetIterator(data),
                                        ShardSpec(0, 2),
                                        drop_partial_round=False))
        b = list(ShardedDataSetIterator(ListDataSetIterator(data),
                                        ShardSpec(1, 2),
                                        drop_partial_round=False))
        assert len(a) == 4 and len(b) == 3  # within-1 tail kept on request

    def test_shard_arg_validation(self):
        it = ListDataSetIterator(_stream(4))
        with pytest.raises(ValueError, match="both index and count"):
            shard(it, count=2)
        with pytest.raises(ValueError, match="both index and count"):
            shard(it, index=1)

    def test_reset_replays(self):
        it = shard(ListDataSetIterator(_stream(6)), 1, 3)
        first = [d.features.sum() for d in it]
        again = [d.features.sum() for d in it]   # __iter__ resets
        assert first == again and len(first) == 2

    def test_explicit_spi_calls(self):
        it = ShardedDataSetIterator(ListDataSetIterator(_stream(4), 4),
                                    ShardSpec(0, 2))
        it.reset()
        n = 0
        while it.hasNext():
            it.next()
            n += 1
        assert n == 2
        assert it.batch() == 4
        with pytest.raises(StopIteration):
            it.next()

    def test_global_step_order_reconstruction(self):
        """step s's global batch = concat of every shard's step-s batch, in
        shard order — the property that makes single-host goldens exact."""
        data = _stream(8)
        its = [list(shard(ListDataSetIterator(data), i, 2)) for i in range(2)]
        for s in range(4):
            np.testing.assert_array_equal(its[0][s].features,
                                          data[2 * s].features)
            np.testing.assert_array_equal(its[1][s].features,
                                          data[2 * s + 1].features)


_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
outdir = sys.argv[4]

from deeplearning4j_tpu.parallel import multihost
multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=nproc, process_id=pid)

import numpy as np, jax.numpy as jnp
import jax.experimental.multihost_utils as mhu
from deeplearning4j_tpu.data import DataSet, ListDataSetIterator, shard
from deeplearning4j_tpu.models import TransformerConfig, init_params
from deeplearning4j_tpu.models.bert import make_train_step, place_params
from deeplearning4j_tpu.parallel.mesh import make_mesh

cfg = TransformerConfig(vocab_size=128, hidden=32, layers=2, heads=4,
                        mlp_dim=64, max_seq=32, remat=False, dtype=jnp.float32)
mesh = make_mesh({"data": jax.device_count()})
init_state, step_fn = make_train_step(cfg, mesh)

# every process builds the SAME deterministic global batch stream, then the
# public shard() API (defaulting to jax.process_index()/process_count())
# hands each one its disjoint round-robin shard — no hand-rolled seeding
rng = np.random.default_rng(7)
B, T = 4, 16
stream = []
for _ in range(8):
    toks = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    stream.append(DataSet(toks, toks))
it = shard(ListDataSetIterator(stream))
assert isinstance(it.spec.count, int) and it.spec.count == nproc

params = place_params(init_params(jax.random.PRNGKey(0), cfg), cfg, mesh)
opt = init_state(params)
steps = 0
for ds in it:
    batch = mhu.host_local_array_to_global_array(
        {"tokens": ds.features, "targets": ds.labels,
         "weights": np.ones((B, T), np.float32)},
        mesh, jax.sharding.PartitionSpec("data"))
    params, opt, loss = step_fn(params, opt, batch)
    steps += 1
assert steps == len(stream) // nproc, steps
flat = np.concatenate([np.ravel(np.asarray(l))
                       for l in jax.tree_util.tree_leaves(params)])
if pid == 0:
    np.save(os.path.join(outdir, "final_params.npy"), flat)
print(f"proc {pid}: DONE steps={steps}", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessShardedData:
    def test_disjoint_shards_match_single_host_golden(self, tmp_path):
        """2 jax.distributed processes, each reading its shard via the
        public shard() API (no hand-rolled per-host seeding): final params
        must equal a single-host run whose step-s batch is the concatenation
        of the shards' step-s batches."""
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), "2", "29881", str(tmp_path)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert "DONE steps=4" in out, out
        got = np.load(tmp_path / "final_params.npy")

        # single-host golden: same stream, global batch = concat of the two
        # shards' step batches (round-robin order: 2s, 2s+1)
        from deeplearning4j_tpu.models import TransformerConfig, init_params
        from deeplearning4j_tpu.models.bert import make_train_step, place_params
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        cfg = TransformerConfig(vocab_size=128, hidden=32, layers=2, heads=4,
                                mlp_dim=64, max_seq=32, remat=False,
                                dtype=jnp.float32)
        rng = np.random.default_rng(7)
        B, T = 4, 16
        stream = [rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
                  for _ in range(8)]
        mesh = make_mesh({"data": 4})
        init_state, step_fn = make_train_step(cfg, mesh)
        params = place_params(init_params(jax.random.PRNGKey(0), cfg),
                              cfg, mesh)
        opt = init_state(params)
        from jax.sharding import NamedSharding, PartitionSpec as P
        bsh = NamedSharding(mesh, P("data"))
        for s in range(4):
            toks = np.concatenate([stream[2 * s], stream[2 * s + 1]])
            batch = {"tokens": jax.device_put(jnp.asarray(toks), bsh),
                     "targets": jax.device_put(jnp.asarray(toks), bsh),
                     "weights": jax.device_put(
                         jnp.ones((2 * B, T), jnp.float32), bsh)}
            params, opt, _ = step_fn(params, opt, batch)
        want = np.concatenate([np.ravel(np.asarray(l))
                               for l in jax.tree_util.tree_leaves(params)])
        np.testing.assert_allclose(got, want, atol=1e-5)
