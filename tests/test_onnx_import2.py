"""ONNX importer round-2 widening: recurrent ops (torch oracle),
ConvTranspose, Resize coordinate modes, einsum/indexing/reduction/activation
stragglers (ref: samediff-import-onnx rule coverage)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from test_onnx_import import make_model, node, run_import  # noqa: E402

RNG = np.random.default_rng(5)


def _f32(*shape):
    return RNG.normal(size=shape).astype(np.float32)


class TestRecurrent:
    def test_lstm_matches_torch(self):
        T, B, I, H = 6, 3, 4, 5
        x = _f32(T, B, I)
        tl = torch.nn.LSTM(I, H, bias=True)
        with torch.no_grad():
            y_t, (h_t, c_t) = tl(torch.from_numpy(x))
        # torch gates IFGO -> ONNX IOFC
        wi = tl.weight_ih_l0.detach().numpy()   # (4H, I) ifgo
        wh = tl.weight_hh_l0.detach().numpy()
        bi = tl.bias_ih_l0.detach().numpy()
        bh = tl.bias_hh_l0.detach().numpy()

        def to_iofc(m):
            i, f, g_, o = np.split(m, 4, axis=0)
            return np.concatenate([i, o, f, g_], axis=0)

        W = to_iofc(wi)[None]                   # (1, 4H, I)
        R = to_iofc(wh)[None]
        Bb = np.concatenate([to_iofc(bi[:, None])[:, 0],
                             to_iofc(bh[:, None])[:, 0]])[None]  # (1, 8H)
        m = make_model(
            [node("LSTM", ["x", "W", "R", "B"], ["Y", "Y_h", "Y_c"],
                  hidden_size=H)],
            inputs=[("x", (T, B, I))], outputs=[("Y", None), ("Y_h", None),
                                                ("Y_c", None)],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": Bb.astype(np.float32)})
        got_y = run_import(m, {"x": x}, "Y")        # (T, 1, B, H)
        np.testing.assert_allclose(got_y[:, 0], y_t.numpy(), atol=1e-5)
        got_h = run_import(m, {"x": x}, "Y_h")
        np.testing.assert_allclose(got_h, h_t.numpy(), atol=1e-5)

    def test_gru_matches_torch_lbr1(self):
        T, B, I, H = 5, 2, 3, 4
        x = _f32(T, B, I)
        tg = torch.nn.GRU(I, H, bias=True)  # torch == linear_before_reset=1
        with torch.no_grad():
            y_t, h_t = tg(torch.from_numpy(x))
        # torch gates RZN -> ONNX ZRH
        wi = tg.weight_ih_l0.detach().numpy()
        wh = tg.weight_hh_l0.detach().numpy()
        bi = tg.bias_ih_l0.detach().numpy()
        bh = tg.bias_hh_l0.detach().numpy()

        def to_zrh(mm):
            r, z, nn_ = np.split(mm, 3, axis=0)
            return np.concatenate([z, r, nn_], axis=0)

        W = to_zrh(wi)[None]
        R = to_zrh(wh)[None]
        Bb = np.concatenate([to_zrh(bi[:, None])[:, 0],
                             to_zrh(bh[:, None])[:, 0]])[None]
        m = make_model(
            [node("GRU", ["x", "W", "R", "B"], ["Y", "Y_h"], hidden_size=H,
                  linear_before_reset=1)],
            inputs=[("x", (T, B, I))], outputs=[("Y", None), ("Y_h", None)],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": Bb.astype(np.float32)})
        got = run_import(m, {"x": x}, "Y")
        np.testing.assert_allclose(got[:, 0], y_t.numpy(), atol=1e-5)

    def test_rnn_bidirectional_shapes_and_tail(self):
        T, B, I, H = 4, 2, 3, 5
        x = _f32(T, B, I)
        W = _f32(2, H, I) * 0.3
        R = _f32(2, H, H) * 0.3
        m = make_model(
            [node("RNN", ["x", "W", "R"], ["Y", "Y_h"], hidden_size=H,
                  direction="bidirectional")],
            inputs=[("x", (T, B, I))], outputs=[("Y", None), ("Y_h", None)],
            initializers={"W": W, "R": R})
        y = run_import(m, {"x": x}, "Y")
        assert y.shape == (T, 2, B, H)
        h = run_import(m, {"x": x}, "Y_h")
        # forward final = last forward step; backward final = output at t=0
        np.testing.assert_allclose(h[0], y[-1, 0], atol=1e-6)
        np.testing.assert_allclose(h[1], y[0, 1], atol=1e-6)

    def test_lstm_sequence_lens_freeze_state(self):
        T, B, I, H = 6, 2, 3, 4
        x = _f32(T, B, I)
        W, R = _f32(1, 4 * H, I) * 0.2, _f32(1, 4 * H, H) * 0.2
        m = make_model(
            [node("LSTM", ["x", "W", "R", "", "lens"], ["Y", "Y_h"],
                  hidden_size=H)],
            inputs=[("x", (T, B, I))], outputs=[("Y", None), ("Y_h", None)],
            initializers={"W": W, "R": R,
                          "lens": np.array([3, 6], np.int32)})
        y = run_import(m, {"x": x}, "Y")[:, 0]      # (T,B,H)
        h = run_import(m, {"x": x}, "Y_h")[0]
        np.testing.assert_allclose(h[0], y[2, 0], atol=1e-6)  # frozen at len 3
        np.testing.assert_allclose(h[1], y[5, 1], atol=1e-6)


class TestConvTransposeResize:
    def test_conv_transpose_matches_torch(self):
        x = _f32(1, 3, 5, 5)
        w = _f32(3, 4, 3, 3) * 0.2  # (C_in, C_out, kH, kW)
        with torch.no_grad():
            want = torch.nn.functional.conv_transpose2d(
                torch.from_numpy(x), torch.from_numpy(w), stride=2).numpy()
        m = make_model(
            [node("ConvTranspose", ["x", "w"], ["y"], kernel_shape=[3, 3],
                  strides=[2, 2])],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"w": w})
        got = run_import(m, {"x": x}, "y")
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_resize_modes_match_torch(self):
        x = _f32(1, 2, 4, 4)
        # linear + align_corners
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(7, 7), mode="bilinear",
            align_corners=True).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="linear",
                  coordinate_transformation_mode="align_corners")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 2, 7, 7], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-5)
        # linear + half_pixel (the default)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(7, 7), mode="bilinear",
            align_corners=False).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="linear",
                  coordinate_transformation_mode="half_pixel")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 2, 7, 7], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-5)
        # nearest + asymmetric + floor == torch 'nearest'
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(7, 7), mode="nearest").numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="nearest",
                  coordinate_transformation_mode="asymmetric",
                  nearest_mode="floor")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 2, 7, 7], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-6)

    def test_upsample_deprecated(self):
        x = _f32(1, 1, 3, 3)
        m = make_model(
            [node("Upsample", ["x", "scales"], ["y"], mode="nearest")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"scales": np.array([1, 1, 2, 2], np.float32)})
        got = run_import(m, {"x": x}, "y")
        np.testing.assert_allclose(got, np.kron(x, np.ones((1, 1, 2, 2),
                                                           np.float32)))


class TestIndexingAndReductions:
    def test_einsum_topk_cumsum(self):
        a, b = _f32(2, 3, 4), _f32(2, 4, 5)
        m = make_model(
            [node("Einsum", ["a", "b"], ["e"], equation="bij,bjk->bik")],
            inputs=[("a", a.shape), ("b", b.shape)], outputs=[("e", None)])
        np.testing.assert_allclose(run_import(m, {"a": a, "b": b}, "e"),
                                   np.einsum("bij,bjk->bik", a, b), atol=1e-5)

        x = _f32(3, 6)
        m = make_model(
            [node("TopK", ["x", "k"], ["v", "i"], axis=-1)],
            inputs=[("x", x.shape)], outputs=[("v", None), ("i", None)],
            initializers={"k": np.array([2], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "v"),
                                   np.sort(x, axis=-1)[:, ::-1][:, :2],
                                   atol=1e-6)

        m = make_model(
            [node("CumSum", ["x", "ax"], ["c"], exclusive=1)],
            inputs=[("x", x.shape)], outputs=[("c", None)],
            initializers={"ax": np.array([1], np.int32)})
        want = np.cumsum(x, 1) - x
        np.testing.assert_allclose(run_import(m, {"x": x}, "c"), want,
                                   atol=1e-5)

    def test_gather_scatter_elements(self):
        x = _f32(3, 4)
        idx = np.array([[0, 2, 1, 3], [3, 0, 0, 1], [1, 1, 2, 2]], np.int64)
        m = make_model(
            [node("GatherElements", ["x", "i"], ["y"], axis=1)],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"i": idx})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"),
                                   np.take_along_axis(x, idx, 1))
        upd = np.zeros((3, 4), np.float32)
        m = make_model(
            [node("ScatterElements", ["x", "i", "u"], ["y"], axis=1)],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"i": idx, "u": upd})
        got = run_import(m, {"x": x}, "y")
        want = x.copy()
        np.put_along_axis(want, idx, upd, 1)
        np.testing.assert_allclose(got, want)

    def test_reduce_variants_and_onehot(self):
        x = _f32(3, 4)
        for opt, ref in [("ReduceL1", np.abs(x).sum(1)),
                         ("ReduceL2", np.sqrt((x ** 2).sum(1))),
                         ("ReduceSumSquare", (x ** 2).sum(1)),
                         ("ReduceLogSumExp",
                          np.log(np.exp(x).sum(1)))]:
            m = make_model([node(opt, ["x"], ["y"], axes=[1], keepdims=0)],
                           inputs=[("x", x.shape)], outputs=[("y", None)])
            np.testing.assert_allclose(run_import(m, {"x": x}, "y"), ref,
                                       atol=1e-5, rtol=1e-5)
        ids = np.array([0, 2, 1], np.int64)
        m = make_model(
            [node("OneHot", ["i", "d", "v"], ["y"])],
            inputs=[("i", ids.shape)], outputs=[("y", None)],
            initializers={"d": np.array([3], np.int64),
                          "v": np.array([0.5, 2.0], np.float32)})
        got = run_import(m, {"i": ids}, "y")
        want = np.full((3, 3), 0.5, np.float32)
        want[np.arange(3), ids] = 2.0
        np.testing.assert_allclose(got, want)

    def test_misc_activations_and_structure(self):
        x = _f32(2, 8, 4, 4)
        m = make_model(
            [node("DepthToSpace", ["x"], ["y"], blocksize=2, mode="CRD")],
            inputs=[("x", x.shape)], outputs=[("y", None)])
        got = run_import(m, {"x": x}, "y")
        want = x.reshape(2, 2, 2, 2, 4, 4).transpose(0, 1, 4, 2, 5, 3) \
                .reshape(2, 2, 8, 8)
        np.testing.assert_allclose(got, want)

        v = _f32(5)
        for opt, kw, ref in [
            ("ThresholdedRelu", {"alpha": 0.3}, np.where(v > 0.3, v, 0)),
            ("Shrink", {"bias": 0.1, "lambd": 0.4},
             np.where(v > 0.4, v - 0.1, np.where(v < -0.4, v + 0.1, 0))),
            ("HardSwish", {}, v * np.clip(v / 6 + 0.5, 0, 1)),
        ]:
            m = make_model([node(opt, ["x"], ["y"], **kw)],
                           inputs=[("x", v.shape)], outputs=[("y", None)])
            np.testing.assert_allclose(run_import(m, {"x": v}, "y"), ref,
                                       atol=1e-5)

        m = make_model(
            [node("Sum", ["a", "b", "c"], ["y"])],
            inputs=[("a", v.shape), ("b", v.shape), ("c", v.shape)],
            outputs=[("y", None)])
        np.testing.assert_allclose(
            run_import(m, {"a": v, "b": v, "c": v}, "y"), 3 * v, atol=1e-6)

        m = make_model(
            [node("Trilu", ["x"], ["y"], upper=0)],
            inputs=[("x", (4, 4))], outputs=[("y", None)])
        xm = _f32(4, 4)
        np.testing.assert_allclose(run_import(m, {"x": xm}, "y"),
                                   np.tril(xm))


class TestReviewRegressions:
    def test_resize_nearest_round_prefer_floor(self):
        """asymmetric + default nearest_mode: 3->4 on [0,1,2] is [0,1,1,2]
        (round-prefer-floor), NOT floor's [0,0,1,2]."""
        x = np.arange(3, dtype=np.float32).reshape(1, 1, 1, 3)
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="nearest",
                  coordinate_transformation_mode="asymmetric")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 1, 1, 4], np.int64)})
        got = run_import(m, {"x": x}, "y")
        np.testing.assert_allclose(got[0, 0, 0], [0, 1, 1, 2])

    def test_topk_smallest(self):
        x = np.array([[1.0, 5.0, 2.0, 4.0, 3.0]], np.float32)
        m = make_model(
            [node("TopK", ["x", "k"], ["v", "i"], largest=0)],
            inputs=[("x", x.shape)], outputs=[("v", None), ("i", None)],
            initializers={"k": np.array([2], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "v"), [[1.0, 2.0]])
        np.testing.assert_array_equal(run_import(m, {"x": x}, "i"), [[0, 2]])

    def test_lstm_dynamic_batch_raises_clearly(self):
        W = _f32(1, 16, 3)
        R = _f32(1, 16, 4)
        m = make_model(
            [node("LSTM", ["x", "W", "R"], ["Y"], hidden_size=4)],
            inputs=[("x", (5, 0, 3))],  # dim_value=0 -> dynamic batch
            outputs=[("Y", None)], initializers={"W": W, "R": R})
        with pytest.raises(ValueError, match="dynamic time/batch"):
            run_import(m, {"x": _f32(5, 2, 3)}, "Y")

    def test_sum_single_input_identity(self):
        v = _f32(4)
        m = make_model([node("Sum", ["x"], ["y"])],
                       inputs=[("x", v.shape)], outputs=[("y", None)])
        np.testing.assert_allclose(run_import(m, {"x": v}, "y"), v)

    def test_scatter_nd_reduction_add(self):
        x = np.ones((4,), np.float32)
        m = make_model(
            [node("ScatterND", ["x", "i", "u"], ["y"], reduction="add")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"i": np.array([[1], [1]], np.int64),
                          "u": np.array([2.0, 3.0], np.float32)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"),
                                   [1.0, 6.0, 1.0, 1.0])
        m = make_model(
            [node("ScatterND", ["x", "i", "u"], ["y"], reduction="mul")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"i": np.array([[1]], np.int64),
                          "u": np.array([2.0], np.float32)})
        with pytest.raises(ValueError, match="reduction 'mul'"):
            run_import(m, {"x": x}, "y")


class TestResizeCubicAndCrop:
    """Round-3 widening: Resize mode=cubic (ONNX a=-0.75) and the
    tf_crop_and_resize coordinate mode (ref: samediff-import-onnx Resize)."""

    def test_resize_cubic_half_pixel_matches_torch(self):
        x = _f32(1, 2, 4, 4)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(7, 7), mode="bicubic",
            align_corners=False).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="cubic",
                  coordinate_transformation_mode="half_pixel")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 2, 7, 7], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-4)

    def test_resize_cubic_align_corners_matches_torch(self):
        x = _f32(1, 1, 5, 5)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(8, 8), mode="bicubic",
            align_corners=True).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="cubic",
                  coordinate_transformation_mode="align_corners")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 1, 8, 8], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-4)

    def test_resize_cubic_downscale(self):
        # downscale exercises taps beyond the 4-neighborhood edge clamps
        x = _f32(1, 2, 8, 8)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(3, 3), mode="bicubic",
            align_corners=False).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="cubic",
                  coordinate_transformation_mode="half_pixel")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 2, 3, 3], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-4)

    def test_resize_tf_crop_and_resize_matches_tf(self):
        import tensorflow as tf
        x = _f32(1, 2, 5, 5)
        box = [0.2, 0.3, 0.9, 0.8]  # y1, x1, y2, x2
        want_nhwc = tf.image.crop_and_resize(
            np.transpose(x, (0, 2, 3, 1)), boxes=[box], box_indices=[0],
            crop_size=(6, 6)).numpy()
        want = np.transpose(want_nhwc, (0, 3, 1, 2))
        roi = np.array([0, 0, box[0], box[1], 1, 1, box[2], box[3]],
                       np.float32)
        m = make_model(
            [node("Resize", ["x", "roi", "", "sizes"], ["y"], mode="linear",
                  coordinate_transformation_mode="tf_crop_and_resize")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"roi": roi,
                          "sizes": np.array([1, 2, 6, 6], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-5)

    def test_resize_tf_crop_and_resize_extrapolates(self):
        import tensorflow as tf
        x = _f32(1, 1, 4, 4)
        box = [-0.2, 0.0, 1.3, 1.0]  # out-of-image rows -> extrapolation
        want_nhwc = tf.image.crop_and_resize(
            np.transpose(x, (0, 2, 3, 1)), boxes=[box], box_indices=[0],
            crop_size=(5, 5), extrapolation_value=7.5).numpy()
        want = np.transpose(want_nhwc, (0, 3, 1, 2))
        roi = np.array([0, 0, box[0], box[1], 1, 1, box[2], box[3]],
                       np.float32)
        m = make_model(
            [node("Resize", ["x", "roi", "", "sizes"], ["y"], mode="linear",
                  coordinate_transformation_mode="tf_crop_and_resize",
                  extrapolation_value=7.5)],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"roi": roi,
                          "sizes": np.array([1, 1, 5, 5], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-5)

    def test_resize_pytorch_half_pixel_outdim1(self):
        # ONNX pytorch_half_pixel maps a length-1 OUTPUT dim to coordinate 0
        # — i.e. exactly input row 0 (the only divergence from half_pixel;
        # torch itself samples src=-0.5 there, so the oracle slices row 0
        # first and resizes only the >1 axis)
        x = _f32(1, 1, 4, 6)
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x[:, :, 0:1, :]), size=(1, 9), mode="bicubic",
            align_corners=False).numpy()
        m = make_model(
            [node("Resize", ["x", "", "", "sizes"], ["y"], mode="cubic",
                  coordinate_transformation_mode="pytorch_half_pixel")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"sizes": np.array([1, 1, 1, 9], np.int64)})
        np.testing.assert_allclose(run_import(m, {"x": x}, "y"), want,
                                   atol=1e-4)

    def test_resize_tf_crop_scales_use_roi_extent(self):
        # output_dim = floor(input_dim * (roi_end - roi_start) * scale)
        import tensorflow as tf
        x = _f32(1, 1, 10, 10)
        box = [0.0, 0.0, 0.5, 0.5]
        want_nhwc = tf.image.crop_and_resize(
            np.transpose(x, (0, 2, 3, 1)), boxes=[box], box_indices=[0],
            crop_size=(10, 10)).numpy()
        want = np.transpose(want_nhwc, (0, 3, 1, 2))
        roi = np.array([0, 0, 0.0, 0.0, 1, 1, 0.5, 0.5], np.float32)
        m = make_model(
            [node("Resize", ["x", "roi", "scales"], ["y"], mode="linear",
                  coordinate_transformation_mode="tf_crop_and_resize")],
            inputs=[("x", x.shape)], outputs=[("y", None)],
            initializers={"roi": roi,
                          "scales": np.array([1, 1, 2, 2], np.float32)})
        got = run_import(m, {"x": x}, "y")
        assert got.shape == (1, 1, 10, 10)
        np.testing.assert_allclose(got, want, atol=1e-5)
