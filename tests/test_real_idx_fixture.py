"""Real-IDX path evidence (VERDICT r1 weak #4: 'the real-IDX path needs at
least one test with a checked-in mini-fixture'). tests/fixtures/mnist holds a
32-image gzipped IDX set in the exact MNIST container layout; pointing the
cache at it must take the real loader path (synthetic flag OFF) and train."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import fetchers

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "mnist")


@pytest.fixture
def mnist_cache(monkeypatch):
    from pathlib import Path
    monkeypatch.setattr(fetchers, "CACHE_DIR", Path(os.path.dirname(FIXTURE)))
    return FIXTURE


class TestRealIdxPath:
    def test_loader_reads_fixture_not_synthetic(self, mnist_cache):
        it = fetchers.MnistDataSetIterator(batch_size=8, train=True, shuffle=False)
        assert it.synthetic is False  # the REAL loader ran
        ds = next(iter(it))
        assert ds.features.shape == (8, 784)
        assert ds.labels.shape == (8, 10)
        f = np.asarray(ds.features)
        assert 0.0 <= f.min() and f.max() <= 1.0
        # fixture labels are 0..9 cyclic; unshuffled first batch = 0..7
        np.testing.assert_array_equal(np.asarray(ds.labels).argmax(-1),
                                      np.arange(8))

    def test_idx_parsing_matches_native_decoder(self, mnist_cache):
        """The gzip+numpy loader and the C++ IDX decoder agree bit-for-bit."""
        import gzip
        import tempfile
        from deeplearning4j_tpu.native import load_idx, native_available
        if not native_available():
            pytest.skip("no native lib")
        gz = os.path.join(FIXTURE, "train-images-idx3-ubyte.gz")
        with gzip.open(gz, "rb") as f:
            raw = f.read()
        with tempfile.NamedTemporaryFile(suffix=".idx", delete=False) as tmp:
            tmp.write(raw)
            path = tmp.name
        try:
            native = load_idx(path, scale=True)
        finally:
            os.unlink(path)
        from pathlib import Path
        py = fetchers._idx_images(Path(gz)).astype(np.float64) / 255.0
        np.testing.assert_allclose(native, py)

    def test_training_on_real_fixture(self, mnist_cache):
        from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.train import Adam
        it = fetchers.MnistDataSetIterator(batch_size=32, train=True, shuffle=False)
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(nOut=32, activation="RELU"))
                .layer(OutputLayer(nOut=10, lossFunction="MCXENT"))
                .setInputType(InputType.feedForward(784)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=30)
        ev = net.evaluate(fetchers.MnistDataSetIterator(batch_size=32,
                                                        train=False, shuffle=False))
        # 32 distinct stroke-count images memorize quickly on the REAL data
        assert ev.accuracy() > 0.9, ev.stats()
