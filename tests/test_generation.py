"""Continuous-batching generation tests: slot-based KV-cache decode with
iteration-level scheduling (serving/generation.py + models/bert.py).

Acceptance criteria exercised here:
- bounded compilation: after varied prompt/output lengths, compiled
  signatures ≤ len(prefill buckets) + ONE decode executable;
- continuous batching: a late-arriving short request starts and finishes
  while an earlier long request is still decoding, with outputs
  bitwise-equal to sequential single-request generation;
- sampling determinism: greedy and top-k streams are bitwise-identical
  for a fixed PRNG key whether a prompt decodes alone or co-scheduled.
"""
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import TransformerConfig, init_params
from deeplearning4j_tpu.serving import (
    CausalLMAdapter, DeadlineExceededError, GenerationEngine, ModelAdapter,
    ModelRegistry, QueueFullError, RejectedError, prefill_buckets,
)

CFG = TransformerConfig(vocab_size=50, hidden=32, layers=2, heads=2,
                        mlp_dim=64, max_seq=64, dtype=jnp.float32,
                        causal=True, attention_impl="full", remat=False)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def eng2(params):
    """Shared (slots=2, max_len=32) engine for tests that only read
    streams — engine construction costs a decode-executable compile, so
    tests that don't assert per-engine counters share one."""
    with GenerationEngine(params, CFG, slots=2, max_len=32) as eng:
        yield eng


@pytest.fixture(scope="module")
def eng4(params):
    """Shared (slots=4, max_len=32) engine for co-scheduling tests."""
    with GenerationEngine(params, CFG, slots=4, max_len=32) as eng:
        yield eng


def prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, n).astype(np.int32)


def _wait_until_decoding(handle, n=1, timeout=60.0):
    """Block until ``handle`` has streamed ≥ n tokens (it holds a slot)."""
    deadline = time.time() + timeout
    while len(handle.tokens_so_far()) < n:
        assert time.time() < deadline, "stream never started"
        time.sleep(0.001)


class TestPrefillBuckets:
    def test_geometric_clamped_ladder(self):
        assert prefill_buckets(32) == (8, 16, 32)
        assert prefill_buckets(8) == (8,)
        # top rung clamps to max_len: non-power-of-two is correct here
        assert prefill_buckets(48) == (8, 16, 32, 48)
        assert prefill_buckets(100) == (8, 16, 32, 64, 100)

    def test_tiny_max_len(self):
        assert prefill_buckets(4) == (4,)
        assert prefill_buckets(1) == (1,)


class TestGreedyGeneration:
    def test_generate_and_repeat_deterministic(self, eng2):
        toks = eng2.generate(prompt(5), max_new_tokens=6, timeout=120)
        assert len(toks) == 6
        assert all(0 <= t < CFG.vocab_size for t in toks)
        assert eng2.generate(prompt(5), max_new_tokens=6,
                             timeout=120) == toks

    def test_eos_retires_stream_early(self, eng2):
        ref = eng2.generate(prompt(5), max_new_tokens=8, timeout=120)
        eos = ref[2]
        k = ref.index(eos)            # first occurrence governs retire
        h = eng2.submit(prompt(5), max_new_tokens=8, eos_id=eos)
        assert h.result(timeout=120) == ref[:k + 1]  # EOS included
        assert h.finish_reason == "eos"
        h2 = eng2.submit(prompt(5), max_new_tokens=8)
        assert h2.result(timeout=120) == ref
        assert h2.finish_reason == "max_tokens"

    def test_stream_yields_incrementally(self, eng2):
        seen = []
        h = eng2.submit(prompt(4, seed=3), max_new_tokens=5,
                        on_token=seen.append)
        streamed = list(h.stream(timeout=120))
        assert streamed == h.result(timeout=5)
        assert seen == streamed
        assert h.tokens_so_far() == streamed

    def test_submit_validation(self, params):
        with GenerationEngine(params, CFG, slots=2, max_len=16,
                              buckets=(4, 8)) as eng:
            with pytest.raises(ValueError):
                eng.submit(np.zeros(0, np.int32))
            with pytest.raises(ValueError):
                eng.submit(prompt(4), max_new_tokens=0)
            with pytest.raises(ValueError):   # prompt + new > max_len
                eng.submit(prompt(10), max_new_tokens=8)
            with pytest.raises(ValueError, match="prefill bucket"):
                eng.submit(prompt(10), max_new_tokens=2)  # > buckets[-1]

    def test_greedy_matches_incremental_forward(self, params, eng2):
        """The KV-cache decode path must predict exactly what the full
        ``forward()`` predicts for the same growing prefix — decode_block
        re-implements the block math against cached K/V, and this is the
        only test that would catch the two paths drifting apart."""
        from deeplearning4j_tpu.models.bert import forward

        p = prompt(5, seed=13)
        out = eng2.generate(p, max_new_tokens=6, timeout=120)
        seq, ref = list(p), []
        for _ in range(6):
            logits = np.asarray(
                forward(params, np.asarray([seq], np.int32), CFG))[0, -1]
            ref.append(int(np.argmax(logits)))
            seq.append(ref[-1])
        assert out == ref

    def test_engine_survives_jit_failure_with_cache_rebuild(
            self, params, tmp_path):
        """A runtime failure in a donated prefill/decode call must not
        brick the engine: live tenants fail, the (possibly consumed) cache
        is rebuilt, and the next request serves normally. Crash dumps for
        the (real, non-injected) failures land in tmp, not the cwd."""
        from deeplearning4j_tpu.util import crash_reporting

        crash_reporting.crashDumpOutputDirectory(str(tmp_path))
        try:
            with GenerationEngine(params, CFG, slots=2, max_len=32) as eng:
                ref = eng.generate(prompt(5), max_new_tokens=4, timeout=120)

                real_prefill = eng._prefill

                def boom(*a, **kw):
                    raise RuntimeError("injected prefill failure")

                eng._prefill = boom
                h = eng.submit(prompt(5), max_new_tokens=4)
                with pytest.raises(RuntimeError, match="injected"):
                    h.result(timeout=30)
                eng._prefill = real_prefill
                assert eng.generate(prompt(5), max_new_tokens=4,
                                    timeout=120) == ref

                real_decode = eng._decode
                mid = eng.submit(prompt(4, seed=2), max_new_tokens=8)
                _wait_until_decoding(mid)
                eng._decode = boom
                with pytest.raises(RuntimeError, match="injected"):
                    mid.result(timeout=30)
                eng._decode = real_decode
                assert eng.generate(prompt(5), max_new_tokens=4,
                                    timeout=120) == ref
        finally:
            crash_reporting.crashDumpOutputDirectory(None)

    def test_needs_causal_config(self, params):
        bidir = TransformerConfig(vocab_size=50, hidden=32, layers=2,
                                  heads=2, mlp_dim=64, max_seq=64,
                                  dtype=jnp.float32, causal=False)
        with pytest.raises(ValueError, match="causal"):
            GenerationEngine(params, bidir, slots=2)


class TestBoundedCompilation:
    def test_varied_lengths_bounded_by_ladder_plus_one(self, params):
        """Acceptance: N requests of varied prompt AND output lengths may
        compile at most len(prefill buckets) prefill signatures + ONE
        decode executable."""
        with GenerationEngine(params, CFG, slots=3, max_len=32) as eng:
            assert eng.buckets == (8, 16, 32)
            rng = np.random.default_rng(7)
            for i in range(12):
                n = int(rng.integers(1, 24))
                out = int(rng.integers(1, 32 - n))
                toks = eng.generate(prompt(n, seed=i), max_new_tokens=out,
                                    timeout=120)
                assert len(toks) <= out
            assert eng._decode._cache_size() == 1
            assert eng.compiled_signatures() <= len(eng.buckets) + 1

    def test_warmup_precompiles_whole_ladder(self, params):
        with GenerationEngine(params, CFG, slots=2, max_len=32) as eng:
            eng.warmup()
            n_sigs = eng.compiled_signatures()
            assert n_sigs == len(eng.buckets) + 1
            # live traffic afterwards mints NO new executables
            for n in (2, 9, 20, 27):
                eng.generate(prompt(n, seed=n), max_new_tokens=3, timeout=120)
            assert eng.compiled_signatures() == n_sigs

    def test_warmup_covers_top_rung_with_one_token_headroom(self, params):
        """A top rung whose prompts leave no room for a 2-token warmup
        stream (here only length 9 maps to rung 10, and 9 + 2 > max_len)
        must still compile — via a 1-token stream — or the first live long
        prompt pays XLA compilation inline."""
        with GenerationEngine(params, CFG, slots=2, max_len=10) as eng:
            assert eng.buckets == (8, 10)
            eng.warmup()
            n_sigs = eng.compiled_signatures()
            assert n_sigs == len(eng.buckets) + 1
            eng.generate(prompt(9, seed=4), max_new_tokens=1, timeout=120)
            assert eng.compiled_signatures() == n_sigs


class TestContinuousBatching:
    def test_late_short_request_overtakes_long_one(self, params):
        """Acceptance: a short request submitted mid-flight of a long one
        starts AND finishes while the long one is still decoding — no
        head-of-line blocking — and both streams are bitwise-equal to
        sequential single-request generation."""
        long_p, short_p = prompt(8, seed=1), prompt(3, seed=2)
        with GenerationEngine(params, CFG, slots=4, max_len=64) as eng:
            # sequential single-request references (engine idle per call)
            ref_long = eng.generate(long_p, max_new_tokens=48, timeout=300)
            ref_short = eng.generate(short_p, max_new_tokens=3, timeout=120)

            h_long = eng.submit(long_p, max_new_tokens=48)
            deadline = time.time() + 60
            while len(h_long.tokens_so_far()) < 2:   # long is mid-decode
                assert time.time() < deadline, "long stream never started"
                time.sleep(0.001)
            h_short = eng.submit(short_p, max_new_tokens=3)
            short_out = h_short.result(timeout=120)
            assert not h_long.future.done(), \
                "long request finished before the short one — not continuous"
            long_out = h_long.result(timeout=300)
        assert short_out == ref_short
        assert long_out == ref_long

    def test_slots_recycle_across_many_requests(self, eng2):
        """More requests than slots: retirement frees slots for queued
        prompts; every stream matches its solo reference."""
        refs = [eng2.generate(prompt(3 + i, seed=i), max_new_tokens=4,
                              timeout=120) for i in range(6)]
        handles = [eng2.submit(prompt(3 + i, seed=i), max_new_tokens=4)
                   for i in range(6)]
        assert [h.result(timeout=120) for h in handles] == refs


class TestSamplingDeterminism:
    @pytest.mark.parametrize("kw", [
        dict(temperature=0.0, top_k=0, seed=11),          # greedy
        dict(temperature=0.7, top_k=5, seed=123),         # top-k sampling
        dict(temperature=1.3, top_k=0, seed=42),          # pure temperature
    ])
    def test_alone_vs_coscheduled_bitwise_identical(self, eng4, kw):
        """A stream's tokens depend only on (params, prompt, PRNG key) —
        never on which slots or neighbors served it."""
        p = prompt(6, seed=9)
        alone = eng4.generate(p, max_new_tokens=8, timeout=120, **kw)
        decoys = [eng4.submit(prompt(4 + i, seed=50 + i),
                              max_new_tokens=20, temperature=0.9,
                              top_k=3, seed=1000 + i) for i in range(3)]
        co = eng4.submit(p, max_new_tokens=8, **kw).result(timeout=120)
        for d in decoys:
            d.result(timeout=120)
        assert co == alone


class TestMeshSharding:
    def test_sharded_engine_streams_bitwise_equal_to_unsharded(self, params,
                                                               eng2):
        """A mesh-sharded engine (params + KV cache over 'model'/'data')
        produces bitwise-identical streams to the unsharded engine —
        including SAMPLED streams: the gumbel draw must run under
        threefry_partitionable, or GSPMD's partitioning of the random op
        over the vocab-sharded logits silently changes the bits."""
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        p = prompt(6, seed=21)
        kw = dict(temperature=0.8, top_k=5, seed=3)
        ref_g = eng2.generate(p, max_new_tokens=6, timeout=120)
        ref_s = eng2.generate(p, max_new_tokens=6, timeout=120, **kw)
        mesh = make_mesh({"data": 4, "model": 2})
        with GenerationEngine(params, CFG, mesh=mesh, slots=2,
                              max_len=32) as eng:
            assert eng.generate(p, max_new_tokens=6, timeout=120) == ref_g
            assert eng.generate(p, max_new_tokens=6, timeout=120,
                                **kw) == ref_s


class TestGenerationAdmission:
    @pytest.fixture(scope="class")
    def eng1(self, params):
        """One-slot engine with a 2-deep queue, shared by the two
        non-destructive admission tests (each drains it fully)."""
        with GenerationEngine(params, CFG, slots=1, max_len=64,
                              queue_capacity=2) as eng:
            yield eng

    def test_queue_full_backpressure(self, eng1):
        blocker = eng1.submit(prompt(2), max_new_tokens=60)
        _wait_until_decoding(blocker)   # slot taken, queue empty again
        held = [eng1.submit(prompt(2, seed=i), max_new_tokens=2)
                for i in (1, 2)]
        with pytest.raises(QueueFullError) as ei:
            eng1.submit(prompt(2, seed=3), max_new_tokens=2)
        assert ei.value.reason == "queue_full"
        assert eng1.metrics.rejected_queue_full.value == 1
        blocker.result(timeout=300)
        for h in held:        # backlog drains once the slot frees
            h.result(timeout=120)

    def test_deadline_sheds_under_full_occupancy(self, eng1):
        """A queued prompt whose deadline expires while every slot is busy
        is shed proactively (expire_queued), not when a slot frees."""
        blocker = eng1.submit(prompt(2), max_new_tokens=60)
        _wait_until_decoding(blocker)   # the only slot is occupied
        doomed = eng1.submit(prompt(3, seed=1), max_new_tokens=2,
                             timeout_ms=20.0)
        with pytest.raises(DeadlineExceededError) as ei:
            doomed.result(timeout=30)
        assert ei.value.reason == "deadline"
        assert not blocker.future.done(), \
            "shed happened lazily at slot-free time, not proactively"
        assert eng1.metrics.rejected_deadline.value >= 1
        blocker.result(timeout=300)

    def test_shutdown_rejects_queued_and_inflight(self, params):
        eng = GenerationEngine(params, CFG, slots=1, max_len=64)
        running = eng.submit(prompt(2), max_new_tokens=60)
        _wait_until_decoding(running, n=2)
        queued = eng.submit(prompt(3, seed=1), max_new_tokens=2)
        eng.shutdown(wait=True)
        with pytest.raises(RejectedError) as ei:
            queued.result(timeout=30)
        assert ei.value.reason == "shutdown"
        with pytest.raises(RejectedError):
            running.result(timeout=30)
        assert len(running.tokens_so_far()) >= 2   # partial stream readable
        with pytest.raises(RejectedError):
            eng.submit(prompt(2), max_new_tokens=2)
        eng.shutdown()   # idempotent
        assert not eng._thread.is_alive()


class TestCausalLMRegistry:
    def test_deploy_and_generate_through_registry(self, params):
        with ModelRegistry() as reg:
            reg.deploy("lm", CausalLMAdapter(params, CFG))
            eng = reg.generation_engine("lm", slots=2, max_len=32)
            toks = eng.generate(prompt(4), max_new_tokens=4, timeout=120)
            assert len(toks) == 4
        assert not eng._thread.is_alive()   # registry shutdown stopped it

    def test_adapter_infer_is_last_position_logits(self, params):
        from deeplearning4j_tpu.models.bert import forward

        adapter = CausalLMAdapter(params, CFG)
        toks = np.stack([prompt(6, seed=1), prompt(6, seed=2)])
        out = adapter.infer(toks)
        expect = np.asarray(forward(params, toks, CFG)[:, -1, :])
        assert out.shape == (2, CFG.vocab_size)
        # jit fuses the [:, -1, :] slice into the forward, so the compiled
        # adapter path and the eager reference differ by reassociation ulps
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    def test_non_generative_deployment_raises(self, params):
        class _Plain(ModelAdapter):
            def infer(self, x):
                return np.asarray(x)

        with ModelRegistry() as reg:
            reg.deploy("plain", _Plain(model=None))
            with pytest.raises(TypeError, match="not generative"):
                reg.generation_engine("plain")

    def test_shutdown_is_idempotent_and_blocks_new_engines(self, params):
        reg = ModelRegistry()
        reg.deploy("lm", CausalLMAdapter(params, CFG))
        eng = reg.generation_engine("lm", slots=2, max_len=32)
        reg.shutdown()
        reg.shutdown()                      # idempotent
        assert not eng._thread.is_alive()
        with pytest.raises(RuntimeError, match="shut down"):
            reg.generation_engine("lm", slots=2, max_len=32)
        assert reg.get("lm").ref == "lm:1"  # deployments stay readable

    def test_adapter_requires_causal_config(self, params):
        bidir = TransformerConfig(vocab_size=50, hidden=32, layers=2,
                                  heads=2, mlp_dim=64, max_seq=64,
                                  dtype=jnp.float32, causal=False)
        with pytest.raises(ValueError, match="causal"):
            CausalLMAdapter(params, bidir)


class TestGenerationMetrics:
    def test_snapshot_and_ui_rollup(self, params):
        import urllib.request

        from deeplearning4j_tpu.ui import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        with GenerationEngine(params, CFG, slots=2, max_len=32) as eng:
            eng.generate(prompt(5), max_new_tokens=6, timeout=120)
            snap = eng.metrics.snapshot()
            assert snap["prefills_total"] == 1
            assert snap["generations_completed"] == 1
            assert snap["generated_tokens_total"] == 6
            assert snap["decode_steps_total"] >= 5
            assert snap["decode_tokens_per_sec"] > 0
            assert 0.0 <= snap["slot_occupancy"] <= 1.0
            assert snap["ttft_ms"]["count"] == 1
            assert snap["decode_step_ms"]["count"] >= 5
            json.dumps(snap)                 # JSON-safe all the way down

            storage = InMemoryStatsStorage()
            eng.metrics.publish(storage)
            server = UIServer(port=0)
            try:
                server.attach(storage)
                with urllib.request.urlopen(server.url + "api/serving",
                                            timeout=5) as r:
                    entries = json.loads(r.read().decode())
                assert len(entries) == 1
                gen = entries[0]["generation"]
                assert gen["decode_tokens_per_sec"] > 0
                assert gen["generations_completed"] == 1
            finally:
                server.stop()

    def test_tokens_per_sec_excludes_prefill_tokens(self):
        from deeplearning4j_tpu.serving import ServingMetrics

        m = ServingMetrics()
        m.prefills_total.inc(2)
        m.generated_tokens_total.inc(12)     # 2 prefill + 10 decode tokens
        m.decode_wall_ms.inc(500.0)
        assert m.decode_tokens_per_sec() == pytest.approx(20.0)
        assert ServingMetrics().decode_tokens_per_sec() == 0.0


@pytest.mark.stress
@pytest.mark.slow
class TestGenerationStress:
    def test_concurrent_clients_soak_bitwise_parity(self, params):
        """8 client threads × 3 rounds of mixed greedy/sampled generations
        against one engine; every stream bitwise-equal to its sequential
        solo reference, signature bound intact throughout."""
        n_clients, rounds = 8, 3
        jobs = {}
        for t in range(n_clients):
            for r in range(rounds):
                kw = (dict(temperature=0.0, top_k=0) if (t + r) % 2 == 0
                      else dict(temperature=0.8, top_k=4))
                jobs[(t, r)] = (prompt(2 + (3 * t + r) % 20, seed=t * 17 + r),
                                dict(max_new_tokens=3 + (t + r) % 6,
                                     seed=t * 100 + r, **kw))
        with GenerationEngine(params, CFG, slots=4, max_len=32,
                              queue_capacity=64) as eng:
            refs = {k: eng.generate(p, timeout=300, **kw)
                    for k, (p, kw) in jobs.items()}
            results, errors = {}, []
            barrier = threading.Barrier(n_clients)

            def client(t):
                try:
                    barrier.wait(timeout=60)
                    for r in range(rounds):
                        p, kw = jobs[(t, r)]
                        results[(t, r)] = eng.generate(p, timeout=300, **kw)
                except Exception as e:  # pragma: no cover - surfaced below
                    errors.append((t, e))

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(n_clients)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=600)
            assert not errors, f"client errors: {errors}"
            assert results == refs
            m = eng.metrics
            assert m.generations_completed.value == 2 * n_clients * rounds
            assert eng.compiled_signatures() <= len(eng.buckets) + 1
            assert eng._decode._cache_size() == 1
