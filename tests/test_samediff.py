"""SameDiff graph engine tests (ref: SameDiffTests / SameDiffTrainingTest in
nd4j platform-tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig, VariableType
from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.train import Adam, Sgd
import jax.numpy as jnp


class TestGraphBuild:
    def test_basic_math(self):
        sd = SameDiff.create()
        a = sd.constant("a", np.array([1.0, 2.0]))
        b = sd.constant("b", np.array([3.0, 4.0]))
        c = a + b
        out = c.eval()
        np.testing.assert_allclose(out.toNumpy(), [4, 6])

    def test_chained_ops_single_graph(self):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3))
        w = sd.var("w", np.ones((3, 2), np.float32))
        b = sd.var("b", np.zeros((2,), np.float32))
        z = x.mmul(w) + b
        y = sd.math.tanh(z).rename("y")
        out = sd.output({"x": np.array([[1.0, 2.0, 3.0]], np.float32)}, "y")["y"]
        np.testing.assert_allclose(out.toNumpy(), np.tanh([[6.0, 6.0]]), rtol=1e-6)

    def test_variable_types(self):
        sd = SameDiff.create()
        v = sd.var("v", np.zeros((2, 2)))
        c = sd.constant("c", 1.0)
        p = sd.placeHolder("p", shape=(2, 2))
        assert v.varType == VariableType.VARIABLE
        assert c.varType == VariableType.CONSTANT
        assert p.varType == VariableType.PLACEHOLDER

    def test_namespaces_and_reductions(self):
        sd = SameDiff.create()
        x = sd.constant("x", np.array([[1.0, 2.0], [3.0, 4.0]]))
        s = x.sum(1)
        m = sd.reduce.mean(x)
        np.testing.assert_allclose(s.eval().toNumpy(), [3, 7])
        assert float(m.eval().toNumpy()) == 2.5

    def test_multi_output_op(self):
        sd = SameDiff.create()
        B, T, I, H = 2, 3, 4, 5
        x = sd.placeHolder("x", shape=(B, T, I))
        h0 = sd.constant("h0", np.zeros((B, H), np.float32))
        c0 = sd.constant("c0", np.zeros((B, H), np.float32))
        w = sd.var("w", np.random.randn(I, 4 * H).astype(np.float32) * 0.1)
        rw = sd.var("rw", np.random.randn(H, 4 * H).astype(np.float32) * 0.1)
        b = sd.var("b", np.zeros((4 * H,), np.float32))
        ys, (hT, cT) = sd.rnn.lstmLayer(x, h0, c0, w, rw, b)
        out = ys.eval({"x": np.random.rand(B, T, I).astype(np.float32)})
        assert out.shape == (B, T, H)
        assert hT.eval({"x": np.random.rand(B, T, I).astype(np.float32)}).shape == (B, H)


class TestGradients:
    def test_calculate_gradients(self):
        sd = SameDiff.create()
        w = sd.var("w", np.array([2.0, 3.0]))
        loss = (w * w).sum().rename("loss")
        sd.setLossVariables("loss")
        grads = sd.calculateGradients({}, ["w"])
        np.testing.assert_allclose(grads["w"].toNumpy(), [4.0, 6.0])
        assert sd.getVariable("w").gradient() is not None

    def test_grad_through_placeholder_graph(self):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 2))
        w = sd.var("w", np.ones((2, 1), np.float32))
        out = sd.math.tanh(x.mmul(w))
        loss = (out * out).sum().rename("loss")
        sd.setLossVariables("loss")
        g = sd.calculateGradients({"x": np.array([[0.5, 0.5]], np.float32)}, ["w"])
        assert g["w"].shape == (2, 1)
        assert np.isfinite(g["w"].toNumpy()).all()


class TestTraining:
    def test_linear_regression(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(256, 3)).astype(np.float32)
        true_w = np.array([[1.5], [-2.0], [0.5]], np.float32)
        Y = X @ true_w + 0.3

        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3))
        y = sd.placeHolder("y", shape=(None, 1))
        w = sd.var("w", np.zeros((3, 1), np.float32))
        b = sd.var("b", np.zeros((1,), np.float32))
        pred = x.mmul(w) + b
        loss = sd.loss.mse(y, pred).rename("loss")
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig(updater=Adam(0.1),
                                            dataSetFeatureMapping=["x"],
                                            dataSetLabelMapping=["y"]))
        ds = DataSet(X, Y)
        history = sd.fit(ListDataSetIterator([ds], batch_size=64), epochs=50)
        assert history[-1] < 0.01
        np.testing.assert_allclose(sd.getVariable("w").getArr().toNumpy(), true_w, atol=0.1)
        np.testing.assert_allclose(float(sd.getVariable("b").getArr().toNumpy()[0]), 0.3, atol=0.1)

    def test_softmax_classifier(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        labels = (X[:, 0] + X[:, 1] > 0).astype(int)
        Y = np.eye(2, dtype=np.float32)[labels]

        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        y = sd.placeHolder("y", shape=(None, 2))
        w = sd.var("w", (4, 2), weightInit="XAVIER", seed=7)
        b = sd.var("b", np.zeros((2,), np.float32))
        logits = x.mmul(w) + b
        probs = sd.nn.softmax(logits).rename("probs")
        loss = sd.loss.mcxent(y, probs).rename("loss")
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig(updater=Adam(0.05),
                                            dataSetFeatureMapping=["x"],
                                            dataSetLabelMapping=["y"]))
        sd.fit(DataSet(X, Y), epochs=100)
        pred = sd.output({"x": X}, "probs")["probs"].toNumpy().argmax(-1)
        assert (pred == labels).mean() > 0.95

    def test_regularization_in_training(self):
        sd = SameDiff.create()
        w = sd.var("w", np.array([10.0], np.float32))
        loss = (w * w).sum().rename("loss")
        sd.setLossVariables("loss")
        from deeplearning4j_tpu.train import L2
        sd.setTrainingConfig(TrainingConfig(updater=Sgd(0.1), regularization=[L2(0.1)]))
        sd.fit({}, epochs=1)  # single empty-placeholder batch
        # dict input path: data={} means one batch with no placeholders
        assert float(sd.getVariable("w").getArr().toNumpy()[0]) < 10.0


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 3))
        w = sd.var("w", np.random.rand(3, 2).astype(np.float32))
        b = sd.var("b", np.zeros((2,), np.float32))
        out = sd.math.tanh(x.mmul(w) + b).rename("out")

        path = str(tmp_path / "model.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)

        xv = np.random.rand(4, 3).astype(np.float32)
        o1 = sd.output({"x": xv}, "out")["out"].toNumpy()
        o2 = sd2.output({"x": xv}, "out")["out"].toNumpy()
        np.testing.assert_allclose(o1, o2, rtol=1e-6)

    def test_batch_output_builder(self):
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 2))
        y = sd.math.exp(x).rename("y")
        out = sd.batchOutput().input("x", np.zeros((1, 2), np.float32)).output("y").execSingle()
        np.testing.assert_allclose(out.toNumpy(), [[1.0, 1.0]])


# ----------------------------------------------------------- control flow
# (ref: InferenceSession Enter/Exit/Merge/Switch — here structured lax
# control flow captured as graph nodes, SURVEY §3.2)

def test_if_cond_both_branches():
    sd = SameDiff.create()
    x = sd.placeHolder("x", shape=(3,), dtype=jnp.float32)
    pred = sd.placeHolder("p", shape=(), dtype=jnp.bool_)
    out = sd.ifCond(pred,
                    lambda s, a: s.math.mul(a, 2.0),
                    lambda s, a: s.math.add(a, 10.0),
                    inputs=[x], name="branchy")
    xs = np.array([1.0, 2.0, 3.0], np.float32)
    hi = sd.output({"x": xs, "p": np.bool_(True)}, [out.name])[out.name].toNumpy()
    lo = sd.output({"x": xs, "p": np.bool_(False)}, [out.name])[out.name].toNumpy()
    np.testing.assert_allclose(hi, xs * 2)
    np.testing.assert_allclose(lo, xs + 10)


def test_while_loop_accumulates():
    sd = SameDiff.create()
    i0 = sd.constant("i0", np.int32(0))
    acc0 = sd.constant("acc0", np.float32(1.0))
    i_out, acc_out = sd.whileLoop(
        [i0, acc0],
        lambda s, i, acc: s.math.lt(i, 5),
        lambda s, i, acc: [s.math.add(i, 1), s.math.mul(acc, 2.0)],
        name="loop")
    res = sd.output({}, [i_out.name, acc_out.name])
    assert int(res[i_out.name].toNumpy()) == 5
    assert float(res[acc_out.name].toNumpy()) == 32.0


def test_for_loop_scan_differentiable():
    """forLoop lowers to lax.scan — gradients flow (the TPU-idiomatic
    trainable loop; plain while has no reverse-mode path, as in XLA)."""
    # loop bodies are self-contained sub-graphs: outer vars enter via state
    sd2 = SameDiff.create()
    w2 = sd2.var("w", np.array([[2.0]], np.float32))
    x = sd2.placeHolder("x", shape=(1, 1), dtype=jnp.float32)
    xN, wN = sd2.forLoop(3, [x, w2],
                         lambda s, i, xx, ww: [s.linalg.matmul(xx, ww), ww],
                         name="powloop")
    val = sd2.output({"x": np.array([[1.0]], np.float32)}, [xN.name])[xN.name]
    assert float(val.toNumpy()) == 8.0  # 2^3
    sd2.setLossVariables(xN.name)
    grads = sd2.calculateGradients({"x": np.array([[1.0]], np.float32)}, ["w"])
    assert abs(float(grads["w"].toNumpy()) - 12.0) < 1e-5  # d(w^3)/dw = 3w^2


def test_grad_through_if_cond():
    sd = SameDiff.create()
    w = sd.var("w", np.array([3.0], np.float32))
    p = sd.placeHolder("p", shape=(), dtype=jnp.bool_)
    out = sd.ifCond(p,
                    lambda s, a: s.math.mul(a, a),      # w^2
                    lambda s, a: s.math.mul(a, 5.0),    # 5w
                    inputs=[w])
    sd.setLossVariables(out.name)
    g_true = sd.calculateGradients({"p": np.bool_(True)}, ["w"])["w"].toNumpy()
    g_false = sd.calculateGradients({"p": np.bool_(False)}, ["w"])["w"].toNumpy()
    np.testing.assert_allclose(g_true, [6.0], atol=1e-6)
    np.testing.assert_allclose(g_false, [5.0], atol=1e-6)


def test_control_flow_save_load_roundtrip(tmp_path):
    sd = SameDiff.create()
    i0 = sd.constant("i0", np.int32(0))
    acc0 = sd.constant("acc0", np.float32(1.0))
    i_out, acc_out = sd.whileLoop(
        [i0, acc0],
        lambda s, i, acc: s.math.lt(i, 4),
        lambda s, i, acc: [s.math.add(i, 1), s.math.mul(acc, 3.0)],
        name="loop")
    p = str(tmp_path / "cf.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    out = sd2.output({}, [acc_out.name])[acc_out.name]
    assert float(out.toNumpy()) == 81.0


class TestEvaluateApi:
    def test_evaluate_classifier(self):
        """sd.evaluate(iterator, output, Evaluation) — ref: SameDiff.evaluate."""
        from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.eval import Evaluation
        rng = np.random.default_rng(4)
        X = rng.normal(size=(128, 4)).astype(np.float32)
        labels = (X.sum(-1) > 0).astype(int)
        Y = np.eye(2, dtype=np.float32)[labels]

        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 4))
        y = sd.placeHolder("y", shape=(None, 2))
        w = sd.var("w", np.zeros((4, 2), np.float32))
        b = sd.var("b", np.zeros((2,), np.float32))
        logits = x.mmul(w) + b
        probs = sd.nn.softmax(logits).rename("probs")
        loss = sd.loss.mcxent(y, probs).rename("loss")
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig(updater=Adam(0.1),
                                            dataSetFeatureMapping=["x"],
                                            dataSetLabelMapping=["y"]))
        it = ListDataSetIterator([DataSet(X, Y)], batch_size=64)
        sd.fit(it, epochs=40)
        ev = sd.evaluate(ListDataSetIterator([DataSet(X, Y)], batch_size=64),
                         "probs", Evaluation())
        assert ev.accuracy() > 0.9, ev.stats()


def test_random_and_updaters_namespaces():
    """sd.random (ref: SDRandom) and sd.updaters (ref: libnd4j updater ops)
    are graph namespaces over the same registry; static args (shape,
    hyperparams) pass as kwargs."""
    import jax
    sd = SameDiff.create()
    k = sd.constant("key", jax.random.PRNGKey(0))
    r = sd.random.normal(k, shape=(4,))
    out = sd.output({}, r.name)[r.name].toNumpy()
    assert out.shape == (4,) and np.isfinite(out).all()

    sd2 = SameDiff.create()
    g = sd2.var("g", np.ones(3, np.float32))
    u = sd2.updaters.sgdUpdater(g, lr=0.5)
    np.testing.assert_allclose(sd2.output({}, u.name)[u.name].toNumpy(), 0.5)


def _fit_parity_model(seed=17):
    rng = np.random.RandomState(seed)
    sd = SameDiff.create()
    x = sd.placeHolder("x", shape=(None, 4))
    y = sd.placeHolder("y", shape=(None, 1))
    w = sd.var("w", (rng.rand(4, 8).astype(np.float32) - 0.5))
    b = sd.var("b", np.zeros((8,), np.float32))
    w2 = sd.var("w2", (rng.rand(8, 1).astype(np.float32) - 0.5))
    h = sd.math.tanh(x.mmul(w) + b)
    loss = sd.loss.mse(y, h.mmul(w2)).rename("loss")
    sd.setLossVariables("loss")
    sd.setTrainingConfig(TrainingConfig(updater=Adam(1e-2),
                                        dataSetFeatureMapping=["x"],
                                        dataSetLabelMapping=["y"]))
    batches = [{"x": rng.rand(8, 4).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)} for _ in range(11)]
    return sd, batches


class TestFusedFit:
    """SameDiff.fit's de-dispatched multi-step path (round 4: fuseSteps
    lax.scan, the fix that took TF-import config #4 from 29k to >100k
    tok/s on TPU) must be loss- and param-identical to the per-step path."""

    def test_fused_matches_per_step(self):
        runs = {}
        for name, fuse in (("fused", 4), ("single", 0)):
            sd, batches = _fit_parity_model()
            sd.fuseSteps = fuse
            hist = sd.fit(batches)   # 11 batches: 2 chunks of 4 + 3 singles
            runs[name] = (hist, {n: np.asarray(sd.getVariable(n).getArr().toNumpy())
                                 for n in ("w", "b", "w2")})
        assert len(runs["fused"][0]) == len(runs["single"][0]) == 11
        np.testing.assert_allclose(runs["fused"][0], runs["single"][0],
                                   rtol=1e-6)
        for n in ("w", "b", "w2"):
            np.testing.assert_allclose(runs["fused"][1][n],
                                       runs["single"][1][n], atol=1e-6)

    def test_unknown_listeners_force_per_step_history(self):
        """A listener WITHOUT requiresModelAtIteration gets the conservative
        per-step path (the fused path may only replay callbacks when the
        listener declared it doesn't need the live model mid-chunk)."""
        calls = []

        class L:
            def iterationDone(self, model, it, ep):
                calls.append((it, float(model.score())))

        sd, batches = _fit_parity_model()
        sd.listeners = [L()]
        hist = sd.fit(batches[:5])
        assert [c[0] for c in calls] == [1, 2, 3, 4, 5]
        np.testing.assert_allclose([c[1] for c in calls], hist, rtol=1e-6)

    def test_score_listener_fuses_with_identical_callbacks(self):
        """Round-5 verdict #2: a score-only listener must NOT de-fuse
        SameDiff.fit (config #4's 146k tok/s has a ScoreListener attached in
        the representative setup) — callback sequence (iteration, score) and
        final params identical to the per-step path, via the same
        _chunk_limit/replay machinery as MultiLayerNetwork."""
        from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

        runs = {}
        for name, fuse in (("fused", 4), ("single", 0)):
            sd, batches = _fit_parity_model()
            sd.fuseSteps = fuse
            seq = []

            class Rec(ScoreIterationListener):
                def iterationDone(self, model, it, ep):
                    seq.append((it, float(model.score())))

            sd.listeners = [Rec()]
            hist = sd.fit(batches)   # 11 batches: 2 chunks of 4 + 3 singles
            runs[name] = (hist, seq,
                          {n: np.asarray(sd.getVariable(n).getArr().toNumpy())
                           for n in ("w", "b", "w2")})
        assert len(runs["fused"][1]) == len(runs["single"][1]) == 11
        assert [i for i, _ in runs["fused"][1]] == \
            [i for i, _ in runs["single"][1]]
        np.testing.assert_allclose([s for _, s in runs["fused"][1]],
                                   [s for _, s in runs["single"][1]],
                                   rtol=1e-6)
        for n in ("w", "b", "w2"):
            np.testing.assert_allclose(runs["fused"][2][n],
                                       runs["single"][2][n], atol=1e-6)

    def test_model_boundary_listener_sees_current_values(self):
        """A listener needing the live model at iteration k observes exactly
        the values the per-step path shows at k (scan flushed there)."""
        snaps = {}

        class SnapAt:
            def __init__(self, tag, at):
                self.tag, self.at = tag, at

            def requiresModelAtIteration(self, it):
                return it in self.at

            def iterationDone(self, model, it, ep):
                if it in self.at:
                    snaps.setdefault(self.tag, {})[it] = np.asarray(
                        model.getVariable("w").getArr().toNumpy()).copy()

        for tag, fuse in (("fused", 4), ("single", 0)):
            sd, batches = _fit_parity_model()
            sd.fuseSteps = fuse
            sd.listeners = [SnapAt(tag, {3, 7})]
            sd.fit(batches)
        for it in (3, 7):
            np.testing.assert_allclose(snaps["fused"][it],
                                       snaps["single"][it], atol=1e-6)

    def test_replay_lag_zero_streams_per_chunk(self):
        """listenerReplayLag=0 (live-streaming mode): callbacks still fire in
        exact order with exact scores — parity with the per-step path."""
        runs = {}
        for name, (fuse, lag) in (("lag0", (4, 0)), ("single", (0, 0))):
            sd, batches = _fit_parity_model()
            sd.fuseSteps = fuse
            sd.listenerReplayLag = lag
            seq = []

            class Rec:
                def requiresModelAtIteration(self, it):
                    return False

                def iterationDone(self, model, it, ep):
                    seq.append((it, float(model.score())))

            sd.listeners = [Rec()]
            sd.fit(batches)
            runs[name] = seq
        assert [i for i, _ in runs["lag0"]] == [i for i, _ in runs["single"]]
        np.testing.assert_allclose([s for _, s in runs["lag0"]],
                                   [s for _, s in runs["single"]], rtol=1e-6)

    def test_exception_mid_fit_preserves_completed_callbacks(self):
        """An exception raised while lagged replays are still BUFFERED must
        not lose the completed chunks' callbacks/scores — the except-path
        drain delivers them. The failure is injected into the THIRD fused
        chunk's dispatch, so two chunks sit undelivered in the replay queue
        at raise time (a shape-mismatched batch would be drained as a
        single and deliver them on the normal path, proving nothing)."""
        sd, batches = _fit_parity_model()
        sd.fuseSteps = 4
        calls = []

        class Rec:
            def requiresModelAtIteration(self, it):
                return False

            def iterationDone(self, model, it, ep):
                calls.append((it, float(model.score())))

        sd.listeners = [Rec()]
        orig = sd._train_multi_fn()
        n = {"calls": 0}

        def bomb(*args):
            n["calls"] += 1
            if n["calls"] == 3:
                raise RuntimeError("injected chunk failure")
            return orig(*args)

        sd._jit_cache["train_multi"] = bomb
        with pytest.raises(RuntimeError, match="injected chunk failure"):
            sd.fit((batches + batches)[:12])   # 3 same-signature chunks of 4
        # the two completed chunks' callbacks arrived, in order
        assert [i for i, _ in calls] == list(range(1, 9))
        assert all(np.isfinite(s) for _, s in calls)

    def test_dtype_change_not_stacked_into_chunk(self):
        """Round-4 advisor: same-shaped batches of different dtypes must not
        np.stack into one fused chunk (silent promotion). Parity with the
        per-step path across an fp32/fp64 batch sequence proves the
        signature split."""
        runs = {}
        for name, fuse in (("fused", 4), ("single", 0)):
            sd, batches = _fit_parity_model()
            sd.fuseSteps = fuse
            mixed = []
            for i, b in enumerate(batches[:8]):
                if i >= 4:
                    b = {k: v.astype(np.float64) for k, v in b.items()}
                mixed.append(b)
            hist = sd.fit(mixed)
            runs[name] = (hist,
                          {n: np.asarray(sd.getVariable(n).getArr().toNumpy())
                           for n in ("w", "b", "w2")})
        np.testing.assert_allclose(runs["fused"][0], runs["single"][0],
                                   rtol=1e-6)
        for n in ("w", "b", "w2"):
            np.testing.assert_allclose(runs["fused"][1][n],
                                       runs["single"][1][n], atol=1e-6)

    def test_shape_change_drains_buffer(self):
        sd, batches = _fit_parity_model()
        small = [{"x": b["x"][:4], "y": b["y"][:4]} for b in batches[:3]]
        hist = sd.fit(batches[:5] + small)
        assert len(hist) == 8
        assert all(np.isfinite(h) for h in hist)


class TestMixedPrecisionTraining:
    """TrainingConfig.computeDtype: bf16 compute over fp32 master params
    (the import-time dtype-rewrite for TF/ONNX-imported graphs — BASELINE.md
    config #4)."""

    def _build(self, compute_dtype):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(128, 6)).astype(np.float32)
        W = rng.normal(size=(6, 3)).astype(np.float32)
        Y = (X @ W + rng.normal(size=(128, 3)) * 0.05).astype(np.float32)
        sd = SameDiff.create()
        x = sd.placeHolder("x", shape=(None, 6))
        y = sd.placeHolder("y", shape=(None, 3))
        w1 = sd.var("w1", (rng.normal(size=(6, 16)) * 0.3).astype(np.float32))
        w2 = sd.var("w2", (rng.normal(size=(16, 3)) * 0.3).astype(np.float32))
        h = sd.math.tanh(x.mmul(w1))
        pred = h.mmul(w2)
        sd.loss.mse(y, pred).rename("loss")
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig(updater=Adam(0.05),
                                            dataSetFeatureMapping=["x"],
                                            dataSetLabelMapping=["y"],
                                            computeDtype=compute_dtype))
        return sd, DataSet(X, Y)

    def test_bf16_trains_to_fp32_quality(self):
        sd32, ds = self._build(None)
        h32 = sd32.fit(ds, epochs=200)
        sd16, ds = self._build("HALF")
        h16 = sd16.fit(ds, epochs=200)
        assert h32[-1] < 0.05
        # bf16 compute converges to the same loss basin (loose tol: 8-bit
        # mantissa), and params stay fp32 masters
        assert h16[-1] < max(2 * h32[-1], 0.08)
        w1 = sd16.getVariable("w1").getArr().jax
        assert w1.dtype == jnp.float32

    def test_compute_dtype_survives_serde(self, tmp_path):
        sd16, ds = self._build("HALF")
        sd16.fit(ds, epochs=2)
        p = str(tmp_path / "mp.zip")
        sd16.save(p, save_updater_state=True)
        back = SameDiff.load(p)
        assert back._training_config.computeDtype == "HALF"
        h = back.fit(ds, epochs=2)
        assert np.isfinite(h[-1])
