"""Fused AdamW (ops/pallas_updaters.py) vs optax.adamw parity.

The fused updater is an opt-in standalone op (and a recorded negative
result for the flagship step — see the module docstring); these tests pin
its math to optax exactly: same params, same state tree, same trajectory.
"""
import functools

import jax
import jax.numpy as jnp
import optax
import pytest

from deeplearning4j_tpu.ops.pallas_updaters import (
    _MIN_PALLAS_SIZE, fused_adamw)


def _tree(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        # lane-divisible and big enough for the pallas path
        "w": jax.random.normal(k1, (max(_MIN_PALLAS_SIZE // 128, 1024), 128)),
        # pallas path with a partial final grid block (rows % block != 0)
        "e": jax.random.normal(k2, (3000, 128)) * 0.1,
        # jnp fallback: tiny and not lane-divisible
        "b": jax.random.normal(k3, (7,)),
    }


@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_trajectory_matches_optax(wd):
    params = _tree(jax.random.PRNGKey(0))
    tx = optax.adamw(3e-3, weight_decay=wd)
    fu = fused_adamw(3e-3, weight_decay=wd, interpret=True)
    st_o, st_f = tx.init(params), fu.init(params)
    p_o, p_f = params, params
    for i in range(4):
        g_o = jax.tree.map(lambda p: jnp.sin(p * (i + 1)), p_o)
        up, st_o = tx.update(g_o, st_o, p_o)
        p_o = optax.apply_updates(p_o, up)
        g_f = jax.tree.map(lambda p: jnp.sin(p * (i + 1)), p_f)
        p_f, st_f = fu.apply(p_f, st_f, g_f)
    for k in params:
        assert jnp.max(jnp.abs(p_o[k] - p_f[k])) < 1e-6, k
    adam_o = next(s for s in st_o if hasattr(s, "mu"))
    adam_f = next(s for s in st_f if hasattr(s, "mu"))
    assert int(adam_o.count) == int(adam_f.count) == 4
    for k in params:
        assert jnp.max(jnp.abs(adam_o.mu[k] - adam_f.mu[k])) < 1e-6, k
        assert jnp.max(jnp.abs(adam_o.nu[k] - adam_f.nu[k])) < 1e-6, k


def test_state_tree_is_optax_shaped():
    """Sharding placement + serde code keys on ScaleByAdamState — the fused
    updater must produce the identical state structure."""
    params = {"w": jnp.ones((256, 128))}
    fu = fused_adamw(1e-3, interpret=True)
    st = fu.init(params)
    new_p, new_st = fu.apply(params, st, params)
    assert jax.tree.structure(st) == jax.tree.structure(new_st)
    assert jax.tree.structure(new_p) == jax.tree.structure(params)


def test_bf16_params_preserve_dtype():
    """bf16 trees (a) keep their dtype through the update (donation-safe),
    (b) track optax.adamw within bf16 resolution on both leaf paths."""
    key = jax.random.PRNGKey(2)
    params = {
        "w": jax.random.normal(key, (1024, 128)).astype(jnp.bfloat16),
        "b": jax.random.normal(key, (7,)).astype(jnp.bfloat16),
    }
    tx = optax.adamw(1e-2, weight_decay=1e-4)
    fu = fused_adamw(1e-2, interpret=True)
    st_o, st_f = tx.init(params), fu.init(params)
    p_o, p_f = params, params
    for i in range(3):
        g_o = jax.tree.map(lambda p: jnp.sin(p.astype(jnp.float32) * (i + 1))
                           .astype(p.dtype), p_o)
        up, st_o = tx.update(g_o, st_o, p_o)
        p_o = optax.apply_updates(p_o, up)
        g_f = jax.tree.map(lambda p: jnp.sin(p.astype(jnp.float32) * (i + 1))
                           .astype(p.dtype), p_f)
        p_f, st_f = fu.apply(p_f, st_f, g_f)
    for k in params:
        assert p_f[k].dtype == params[k].dtype, k
        d = jnp.max(jnp.abs(p_o[k].astype(jnp.float32)
                            - p_f[k].astype(jnp.float32)))
        assert d < 3e-2, (k, float(d))


def test_default_weight_decay_matches_optax():
    """Drop-in contract: identical defaults, incl. weight_decay=1e-4."""
    params = {"w": jnp.full((256, 128), 2.0)}
    tx, fu = optax.adamw(1e-2), fused_adamw(1e-2, interpret=True)
    up, _ = tx.update(jax.tree.map(jnp.ones_like, params), tx.init(params),
                      params)
    p_o = optax.apply_updates(params, up)
    p_f, _ = fu.apply(params, fu.init(params), jax.tree.map(jnp.ones_like,
                                                            params))
    assert jnp.max(jnp.abs(p_o["w"] - p_f["w"])) < 1e-6


def test_jit_donation_compatible():
    """One donated jitted step — the deployment shape."""
    params = _tree(jax.random.PRNGKey(1))
    fu = fused_adamw(1e-3, interpret=True)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, st, g):
        return fu.apply(p, st, g)

    st = fu.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    p2, st2 = step(params, st, g)
    assert jnp.all(jnp.isfinite(p2["w"]))
    assert int(next(s for s in st2 if hasattr(s, "mu")).count) == 1
