"""Join + sequence-transform tests (ref: datavec TestJoin +
TestSequenceTransforms)."""
import pytest

from deeplearning4j_tpu.datavec.join import Join
from deeplearning4j_tpu.datavec.schema import Schema
from deeplearning4j_tpu.datavec.sequence import (
    convertToSequence, offsetSequence, reduceSequence,
    sequenceMovingWindowReduce, splitSequenceOnGap, trimSequence,
    windowSequence,
)
from deeplearning4j_tpu.datavec.writables import (
    DoubleWritable, IntWritable, NullWritable, Text,
)


def W(v):
    if isinstance(v, str):
        return Text(v)
    if isinstance(v, int):
        return IntWritable(v)
    return DoubleWritable(v)


def rows(*data):
    return [[W(v) for v in r] for r in data]


def left_schema():
    return (Schema.Builder().addColumnString("id")
            .addColumnDouble("price").build())


def right_schema():
    return (Schema.Builder().addColumnString("id")
            .addColumnString("category").build())


LEFT = rows(("a", 1.0), ("b", 2.0), ("c", 3.0))
RIGHT = rows(("a", "fruit"), ("b", "veg"), ("d", "meat"))


class TestJoin:
    def test_inner(self):
        j = Join("Inner", left_schema(), right_schema(), ["id"])
        out = j.execute(LEFT, RIGHT)
        assert [[w.toString() for w in r] for r in out] == [
            ["a", "1.0", "fruit"], ["b", "2.0", "veg"]]
        assert j.getOutputSchema().getColumnNames() == ["id", "price", "category"]

    def test_left_outer(self):
        out = Join("LeftOuter", left_schema(), right_schema(), ["id"]).execute(LEFT, RIGHT)
        assert len(out) == 3
        assert isinstance(out[2][2], NullWritable)  # 'c' has no category

    def test_right_outer(self):
        out = Join("RightOuter", left_schema(), right_schema(), ["id"]).execute(LEFT, RIGHT)
        ids = sorted(r[0].toString() for r in out)
        assert ids == ["a", "b", "d"]
        d_row = next(r for r in out if r[0].toString() == "d")
        assert isinstance(d_row[1], NullWritable)   # no price
        assert d_row[2].toString() == "meat"

    def test_full_outer(self):
        out = Join("FullOuter", left_schema(), right_schema(), ["id"]).execute(LEFT, RIGHT)
        assert sorted(r[0].toString() for r in out) == ["a", "b", "c", "d"]

    def test_one_to_many(self):
        right = rows(("a", "x"), ("a", "y"))
        out = Join("Inner", left_schema(), right_schema(), ["id"]).execute(LEFT, right)
        assert len(out) == 2
        assert {r[2].toString() for r in out} == {"x", "y"}


def seq_schema():
    return (Schema.Builder().addColumnString("dev")
            .addColumnInteger("t").addColumnDouble("v").build())


class TestSequence:
    def test_convert_to_sequence_groups_and_sorts(self):
        flat = rows(("d1", 3, 30.0), ("d2", 1, 100.0), ("d1", 1, 10.0),
                    ("d1", 2, 20.0), ("d2", 2, 200.0))
        seqs = convertToSequence(flat, seq_schema(), "dev", "t")
        assert len(seqs) == 2
        assert [r[2].toDouble() for r in seqs[0]] == [10.0, 20.0, 30.0]
        assert [r[2].toDouble() for r in seqs[1]] == [100.0, 200.0]

    def test_trim(self):
        seq = rows(("d", 1, 1.0), ("d", 2, 2.0), ("d", 3, 3.0))
        assert [r[1].toInt() for r in trimSequence(seq, 1, True)] == [2, 3]
        assert [r[1].toInt() for r in trimSequence(seq, 2, False)] == [1]

    def test_offset_lag_feature(self):
        seq = rows(("d", 1, 10.0), ("d", 2, 20.0), ("d", 3, 30.0))
        out = offsetSequence(seq, seq_schema(), ["v"], offset=1, op="NewColumn")
        # step t carries v[t-1]; first step trimmed
        assert len(out) == 2
        assert out[0][3].toDouble() == 10.0 and out[0][2].toDouble() == 20.0
        assert out[1][3].toDouble() == 20.0

    def test_reduce_sequence(self):
        seq = rows(("d", 1, 10.0), ("d", 2, 30.0))
        red = reduceSequence(seq, seq_schema(), {"v": "mean", "t": "count"})
        assert red[0].toDouble() == 20.0 and red[1].toInt() == 2

    def test_windows_overlapping_and_tumbling(self):
        seq = rows(*[("d", i, float(i)) for i in range(6)])
        over = windowSequence(seq, windowSize=3, step=1)
        assert len(over) == 4
        assert [r[1].toInt() for r in over[1]] == [1, 2, 3]
        tumb = windowSequence(seq, windowSize=2, step=2)
        assert len(tumb) == 3
        assert [r[1].toInt() for r in tumb[2]] == [4, 5]

    def test_split_on_time_gap(self):
        seq = rows(("d", 1, 0.0), ("d", 2, 0.0), ("d", 10, 0.0), ("d", 11, 0.0))
        parts = splitSequenceOnGap(seq, seq_schema(), "t", maxGap=3)
        assert [len(p) for p in parts] == [2, 2]
        assert parts[1][0][1].toInt() == 10

    def test_moving_window_reduce(self):
        seq = rows(*[("d", i, float(i)) for i in range(5)])
        out = sequenceMovingWindowReduce(seq, seq_schema(), "v", window=3,
                                         agg="mean")
        assert len(out) == 3  # warmup trimmed
        assert out[0][3].toDouble() == pytest.approx(1.0)  # mean(0,1,2)
        assert out[2][3].toDouble() == pytest.approx(3.0)  # mean(2,3,4)


class TestTransformProcessSequenceMode:
    def test_pipeline_rows_to_sequences(self):
        """Builder pipeline: row math -> convertToSequence -> lag feature ->
        moving mean; executed via executeToSequence with schema tracking
        (ref: LocalTransformExecutor.executeToSequence)."""
        from deeplearning4j_tpu.datavec.transform import TransformProcess
        schema = seq_schema()
        tp = (TransformProcess.Builder(schema)
              .doubleMathOp("v", "Multiply", 2.0)
              .convertToSequence("dev", "t")
              .offsetSequence(["v"], 1, op="NewColumn")
              .sequenceMovingWindowReduce("v", 2, agg="mean")
              .build())
        flat = rows(("d1", 2, 2.0), ("d1", 1, 1.0), ("d1", 3, 3.0),
                    ("d2", 1, 10.0), ("d2", 2, 20.0))
        seqs = tp.executeToSequence(flat)
        final = tp.getFinalSchema()
        assert final.getColumnNames() == ["dev", "t", "v", "v_offset1",
                                          "mean(v,2)"]
        # d1: v doubled -> [2,4,6] sorted by t; lag drops t=1; window-2 mean
        # then drops the first remaining step
        d1 = seqs[0]
        assert [r[1].toInt() for r in d1] == [3]
        assert d1[0][2].toDouble() == 6.0          # v at t=3
        assert d1[0][3].toDouble() == 4.0          # lag-1 (t=2 value)
        assert d1[0][4].toDouble() == pytest.approx(5.0)  # mean(4, 6)

    def test_execute_rejects_sequence_steps(self):
        from deeplearning4j_tpu.datavec.transform import TransformProcess
        tp = (TransformProcess.Builder(seq_schema())
              .convertToSequence("dev", "t").build())
        with pytest.raises(ValueError, match="executeToSequence"):
            tp.execute(rows(("d", 1, 1.0)))

    def test_sequence_process_json_roundtrip(self):
        from deeplearning4j_tpu.datavec.transform import TransformProcess
        tp = (TransformProcess.Builder(seq_schema())
              .convertToSequence("dev", "t")
              .trimSequence(1, fromFirst=True)
              .offsetSequence(["v"], 1)
              .build())
        tp2 = TransformProcess.from_json(tp.to_json())
        flat = rows(("d", 1, 1.0), ("d", 2, 2.0), ("d", 3, 3.0))
        a = tp.executeToSequence(flat)
        b = tp2.executeToSequence(flat)
        assert [[w.toString() for w in r] for q in a for r in q] == \
               [[w.toString() for w in r] for q in b for r in q]
