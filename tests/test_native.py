"""Native C++ data-pipeline tests: parity native-vs-python on every entry
point, IDX fixtures for all dtypes, prefetcher semantics (ref: the
reference's datavec native IO tests + AsyncDataSetIteratorTest)."""
import struct
import time

import numpy as np
import pytest

from deeplearning4j_tpu.native import (
    PrefetchIterator, load_idx, native_available, parse_csv,
)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib unavailable (no compiler)")

RNG = np.random.default_rng(2)


class TestCsv:
    def test_native_matches_python_and_truth(self):
        arr = RNG.normal(size=(500, 7))
        text = "\n".join(",".join(f"{v:.8f}" for v in row) for row in arr)
        a = parse_csv(text)
        b = parse_csv(text, force_python=True)
        np.testing.assert_allclose(a, b)
        np.testing.assert_allclose(a, arr, atol=1e-7)

    def test_file_path_and_delimiters(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text("1;2;3\n4;5;6\n")
        np.testing.assert_allclose(parse_csv(str(p), delimiter=";"),
                                   [[1, 2, 3], [4, 5, 6]])

    def test_crlf_and_blank_lines(self):
        got = parse_csv("1,2\r\n\r\n3,4\r\n")
        np.testing.assert_allclose(got, [[1, 2], [3, 4]])

    def test_non_numeric_fields_become_nan(self):
        got = parse_csv("1,abc,3\n4,5,xyz\n")
        assert np.isnan(got[0, 1]) and np.isnan(got[1, 2])
        assert got[0, 0] == 1 and got[1, 1] == 5

    def test_multithreaded_large_parse(self):
        arr = RNG.normal(size=(5000, 12))
        text = "\n".join(",".join(f"{v:.6f}" for v in row) for row in arr)
        got = parse_csv(text, threads=8)
        np.testing.assert_allclose(got, arr, atol=1e-6)

    def test_native_not_slower_than_python(self):
        arr = RNG.normal(size=(10000, 16))
        text = "\n".join(",".join(f"{v:.6f}" for v in row) for row in arr)
        t0 = time.perf_counter()
        parse_csv(text)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        parse_csv(text, force_python=True)
        t_python = time.perf_counter() - t0
        assert t_native < t_python  # measured ~2-3x faster


def write_idx(path, arr, dtype_code):
    """Big-endian IDX container writer (test fixture)."""
    enc = {0x08: ">u1", 0x09: ">i1", 0x0B: ">i2", 0x0C: ">i4",
           0x0D: ">f4", 0x0E: ">f8"}[dtype_code]
    with open(path, "wb") as f:
        f.write(bytes([0, 0, dtype_code, arr.ndim]))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(np.ascontiguousarray(arr, dtype=enc).tobytes())


class TestIdx:
    @pytest.mark.parametrize("code,maker", [
        (0x08, lambda: RNG.integers(0, 256, (10, 4, 4)).astype(np.uint8)),
        (0x09, lambda: RNG.integers(-128, 128, (20,)).astype(np.int8)),
        (0x0B, lambda: RNG.integers(-30000, 30000, (6, 3)).astype(np.int16)),
        (0x0C, lambda: RNG.integers(-10**9, 10**9, (5, 2)).astype(np.int32)),
        (0x0D, lambda: RNG.normal(size=(7, 3)).astype(np.float32)),
        (0x0E, lambda: RNG.normal(size=(4, 4))),
    ])
    def test_all_dtypes_native_matches_python(self, tmp_path, code, maker):
        arr = maker()
        p = str(tmp_path / "f.idx")
        write_idx(p, arr, code)
        a = load_idx(p)
        b = load_idx(p, force_python=True)
        np.testing.assert_allclose(a, b)
        np.testing.assert_allclose(a, arr.astype(np.float64), rtol=1e-6)

    def test_uint8_scaling(self, tmp_path):
        arr = np.array([[0, 128, 255]], np.uint8)
        p = str(tmp_path / "img.idx")
        write_idx(p, arr, 0x08)
        got = load_idx(p, scale=True)
        np.testing.assert_allclose(got, [[0.0, 128 / 255, 1.0]])

    def test_mnist_shaped_container(self, tmp_path):
        """A realistic MNIST-like fixture through the native decoder — the
        real-IDX path evidence VERDICT r1 asked for."""
        imgs = RNG.integers(0, 256, (32, 28, 28)).astype(np.uint8)
        p = str(tmp_path / "images-idx3-ubyte")
        write_idx(p, imgs, 0x08)
        got = load_idx(p, scale=True)
        assert got.shape == (32, 28, 28)
        np.testing.assert_allclose(got, imgs / 255.0)

    def test_malformed_raises(self, tmp_path):
        p = tmp_path / "bad.idx"
        p.write_bytes(b"\x01\x02\x03\x04")
        with pytest.raises(ValueError, match="malformed"):
            load_idx(str(p))


class TestPrefetch:
    def test_order_and_completeness(self):
        items = list(range(50))
        got = list(PrefetchIterator(items, depth=4))
        assert got == items

    def test_overlaps_producer_and_consumer(self):
        def slow_gen():
            for i in range(5):
                time.sleep(0.05)
                yield i

        t0 = time.perf_counter()
        for _ in PrefetchIterator(slow_gen(), depth=2):
            time.sleep(0.05)  # consumer work overlaps producer sleeps
        overlapped = time.perf_counter() - t0
        assert overlapped < 0.45  # serial would be ~0.5s

    def test_exception_propagates(self):
        def boom():
            yield 1
            raise RuntimeError("etl failed")

        it = iter(PrefetchIterator(boom()))
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="etl failed"):
            next(it)

    def test_reusable(self):
        pf = PrefetchIterator([1, 2, 3], depth=1)
        assert list(pf) == [1, 2, 3]
        assert list(pf) == [1, 2, 3]


def test_trailing_empty_field_is_nan_not_next_row():
    """Regression: strtod must not skip the newline and consume the next
    row's first value for an empty trailing field."""
    got = parse_csv("1,2,\n3,4,5\n")
    want = parse_csv("1,2,\n3,4,5\n", force_python=True)
    assert np.isnan(got[0, 2]) and np.isnan(want[0, 2])
    np.testing.assert_allclose(got[1], [3, 4, 5])
