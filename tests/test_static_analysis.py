"""Static-analysis suite tests (tools/analysis — ISSUE 8, v2 per
ISSUE 11).

Three layers, per the acceptance criteria:

1. **Fixture proofs** — every checker (the five ISSUE 8 rules plus the
   ISSUE 11 cluster-era rules: wire-schema-drift, deadline-propagation,
   metrics-drift, exception-chaining) has at least one proven true
   positive and one clean negative on small snippets modeled on the
   serving stack's real shapes; the transitive call expansion the v2
   lock-discipline/donation-safety checkers grew has depth proofs.
2. **Reintroduction gates** — deliberately re-introducing one known
   past bug per class (the blocking-under-admission-lock shape PR 1's
   review caught, the use-after-donate zombie decode PRs 3/6 fixed,
   PR 7's taxonomy drift, a raw engine ``set_exception`` skipping
   accounting, PR 8's serving-layer ``jax.jit``, the PR 10
   heartbeat-seq wire asymmetry, and ISSUE 11's own
   lost-cause-in-except from generation.py) makes the corresponding
   checker fail.
3. **The real-package gate** — ``python -m tools.analysis`` over
   serving/ + models/ + ops/ + tools/ + ui/server.py exits 0 with zero
   unsuppressed findings, in under 10 seconds, and the suppression +
   baseline + --changed-only mechanisms round-trip.

Pure stdlib: none of these tests import jax or the serving modules —
the analyzer is syntactic by design.
"""
import configparser
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analysis import (
    Baseline, all_checkers, analyze_paths, analyze_sources,
)

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]
SERVING = str(REPO / "deeplearning4j_tpu" / "serving")
MODELS = str(REPO / "deeplearning4j_tpu" / "models")
OPS = str(REPO / "deeplearning4j_tpu" / "ops")
TOOLS = str(REPO / "tools")
UI_SERVER = str(REPO / "deeplearning4j_tpu" / "ui" / "server.py")
#: the ISSUE 11 whole-package gate scope
GATE_SCOPE = [SERVING, MODELS, OPS, TOOLS, UI_SERVER]
DEFAULT_BASELINE = str(REPO / "tools" / "analysis" / "baseline.json")

RULES = {c.rule for c in all_checkers()}


def run(sources, rules=None, baseline=None):
    return analyze_sources(sources, rules=rules, baseline=baseline)


def rules_hit(report):
    return {f.rule for f in report.unsuppressed}


# --------------------------------------------------------------------------
# 1. lock-discipline
# --------------------------------------------------------------------------
LOCK_TP = '''
import time
class Engine:
    def shed_under_lock(self, req):          # PR 1 review bug shape
        with self._cv:
            req.future.result()
    def sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)
    def dispatch_under_lock(self, batch):
        with self._wd_lock:
            self._dispatch(batch)
    def relock(self):
        with self._lock:
            with self._lock:
                pass
    def order_ab(self):
        with self._wd_lock:
            with self._prefix_lock:
                pass
    def order_ba(self):
        with self._prefix_lock:
            with self._wd_lock:
                pass
    def relock_via_call(self):
        with self._prefix_lock:
            self.usable()
    def usable(self):
        with self._prefix_lock:
            return 1
'''

LOCK_NEG = '''
class Controller:
    def take(self, timeout):
        shed = []
        with self._cv:
            self._cv.wait(timeout)           # wait on the HELD cv: fine
            if self._q:
                shed.append(self._q.popleft())
        for req in shed:                     # futures failed OUTSIDE
            self._shed(req)
        return None
    def ordered_only(self):
        with self._wd_lock:
            with self._prefix_lock:          # one global order: fine
                pass
    def helper_no_locks(self):
        with self._lock:
            self.pure()                      # callee takes no locks
    def pure(self):
        return ", ".join(["a", "b"])         # str.join: not thread join
'''


class TestLockDiscipline:
    def test_true_positives(self):
        r = run({"serving/eng.py": LOCK_TP}, rules=["lock-discipline"])
        msgs = [f.message for f in r.unsuppressed]
        assert any(".result()" in m for m in msgs)
        assert any("time.sleep" in m for m in msgs)
        assert any("_dispatch" in m for m in msgs)
        assert any("re-acquisition" in m for m in msgs)
        assert any("inversion" in m for m in msgs)
        assert any("self.usable" in m for m in msgs)   # call-expansion

    def test_clean_negative(self):
        r = run({"serving/ctl.py": LOCK_NEG}, rules=["lock-discipline"])
        assert r.unsuppressed == []

    def test_same_named_classes_in_different_files_do_not_merge(self):
        """Two unrelated classes that happen to share a name must keep
        separate lock graphs: merging them fabricates an inversion
        spanning classes that never share an instance (and transitive
        expansion would walk the wrong class's methods)."""
        a = ("class Manager:\n"
             "    def f(self):\n"
             "        with self._a_lock:\n"
             "            with self._b_lock:\n"
             "                pass\n")
        b = ("class Manager:\n"
             "    def g(self):\n"
             "        with self._b_lock:\n"
             "            with self._a_lock:\n"
             "                pass\n")
        r = run({"serving/a.py": a, "serving/b.py": b},
                rules=["lock-discipline"])
        assert r.unsuppressed == []
        # sanity: the same two orders INSIDE one class still invert
        r2 = run({"serving/a.py": a.replace(
            "    def f", "    def g(self):\n"
            "        with self._b_lock:\n"
            "            with self._a_lock:\n"
            "                pass\n"
            "    def f")}, rules=["lock-discipline"])
        assert any("inversion" in f.message for f in r2.unsuppressed)

    def test_multi_item_with_statement(self):
        """Review regression: ``with a, b:`` acquires left to right —
        the items must relock-check and order-edge against EACH OTHER,
        not just against outer with-statements."""
        src = '''
class E:
    def relock(self):
        with self._lock, self._lock:
            pass
    def ab(self):
        with self._a_lock, self._b_lock:
            pass
    def ba(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        msgs = [f.message for f in r.unsuppressed]
        assert any("re-acquisition" in m for m in msgs)
        assert any("inversion" in m for m in msgs)

    def test_reintroduce_blocking_result_under_admission_lock(self):
        """Acceptance: the exact past bug — failing shed futures while
        still holding the admission condition lock."""
        bug = LOCK_NEG.replace(
            "for req in shed:                     # futures failed OUTSIDE\n"
            "            self._shed(req)",
            "    for req in shed:\n"
            "                req.future.result()")
        r = run({"serving/ctl.py": bug}, rules=["lock-discipline"])
        assert rules_hit(r) == {"lock-discipline"}


# --------------------------------------------------------------------------
# 2. donation-safety
# --------------------------------------------------------------------------
DONATION_TP = '''
class Engine:
    def decode_iteration(self):              # PR 3/6 zombie-decode shape
        cache = self._cache
        new_cache, toks = self._decode(self.params, cache, self._tables)
        lengths = cache["lengths"]           # use-after-donate
        return toks, lengths
'''

DONATION_NEG = '''
class Engine:
    def decode_iteration(self, epoch):
        cache = self._cache                  # snapshot (zombie-safe)
        new_cache, toks = self._decode(self.params, cache, self._tables)
        with self._wd_lock:
            if self._epoch == epoch:         # epoch guard
                self._cache = new_cache
        return toks
    def retry_closure(self):
        def call():
            return self._donated_call(
                "generation.prefill", self._prefill,
                self.params, self._cache, self.row)
        return self._retry_call(call)        # per-attempt re-read: safe
'''


class TestDonationSafety:
    def test_true_positive(self):
        r = run({"serving/gen.py": DONATION_TP}, rules=["donation-safety"])
        assert rules_hit(r) == {"donation-safety"}
        assert any("use-after-donate" in f.message for f in r.unsuppressed)

    def test_clean_negative(self):
        r = run({"serving/gen.py": DONATION_NEG}, rules=["donation-safety"])
        assert r.unsuppressed == []

    def test_same_line_writeback_is_a_rebind(self):
        """Review regression: the canonical writeback shape
        ``self._cache, toks = self._decode(..., self._cache, ...)``
        leaves the binding holding the FRESH cache — reading it
        afterwards is safe and must not be flagged."""
        src = '''
class Engine:
    def decode(self, tokens):
        self._cache, toks = self._decode(self.params, self._cache, tokens)
        return self._cache["lengths"], toks
'''
        r = run({"serving/gen.py": src}, rules=["donation-safety"])
        assert r.unsuppressed == []

    def test_read_and_rebind_in_one_statement_still_flagged(self):
        """Review regression: ``self._cache = trim(self._cache)`` after
        a donation READS the consumed buffers before rebinding — the
        same-line Store must not mask the Load (RHS evaluates first)."""
        src = '''
class Engine:
    def decode(self, tokens):
        new_cache, toks = self._decode(self.params, self._cache, tokens)
        self._cache = trim(self._cache)
        return toks
'''
        r = run({"serving/gen.py": src}, rules=["donation-safety"])
        assert rules_hit(r) == {"donation-safety"}

    def test_reintroduce_rereading_donated_self_cache(self):
        """Acceptance: re-reading self._cache for a second donated call
        with no rebind between them — the 'Array has been deleted'
        engine-bricking class."""
        bug = '''
class Engine:
    def double_dispatch(self, tokens):
        c1, t1 = self._decode(self.params, self._cache, tokens)
        c2, t2 = self._decode(self.params, self._cache, tokens)
        return t2
'''
        r = run({"serving/gen.py": bug}, rules=["donation-safety"])
        assert rules_hit(r) == {"donation-safety"}


# --------------------------------------------------------------------------
# 3. taxonomy-drift
# --------------------------------------------------------------------------
TAXONOMY_NEG = '''
TERMINAL_REASONS = ("ok", "queue_full", "deadline", "shutdown")
class RejectedError(RuntimeError):
    def __init__(self, msg, reason):
        super().__init__(msg)
        self.reason = reason
class QueueFullError(RejectedError):
    def __init__(self, msg):
        super().__init__(msg, "queue_full")
class Mixin:
    def _reject(self, exc):
        self.metrics.record_rejection(exc.reason)   # dynamic routing
'''


class TestTaxonomyDrift:
    def test_unregistered_subclass_reason(self):
        """Acceptance (PR 7's class): a new typed shed whose reason is
        missing from TERMINAL_REASONS fails the lint."""
        src = TAXONOMY_NEG + '''
class BrandNewShedError(RejectedError):
    def __init__(self, msg):
        super().__init__(msg, "brand_new_reason")
'''
        r = run({"serving/t.py": src}, rules=["taxonomy-drift"])
        assert rules_hit(r) == {"taxonomy-drift"}
        assert any("BrandNewShedError" in f.message for f in r.unsuppressed)

    def test_duplicate_reason_in_taxonomy(self):
        src = TAXONOMY_NEG.replace('"deadline", "shutdown"',
                                   '"deadline", "deadline"')
        r = run({"serving/t.py": src}, rules=["taxonomy-drift"])
        assert any("2 times" in f.message for f in r.unsuppressed)

    def test_literal_recording_site_drift(self):
        src = TAXONOMY_NEG + '''
def f(metrics):
    metrics.record_rejection("typo_reason")
'''
        r = run({"serving/t.py": src}, rules=["taxonomy-drift"])
        assert any("typo_reason" in f.message for f in r.unsuppressed)

    def test_uncounted_reason(self):
        """A reason in the taxonomy that nothing can ever count (no
        literal record_rejection, no dynamic routing) is drift too."""
        src = '''
TERMINAL_REASONS = ("ok", "orphan_reason")
class RejectedError(RuntimeError):
    def __init__(self, msg, reason):
        super().__init__(msg)
        self.reason = reason
class OrphanError(RejectedError):
    def __init__(self, msg):
        super().__init__(msg, "orphan_reason")
'''
        r = run({"serving/t.py": src}, rules=["taxonomy-drift"])
        assert any("never counted" in f.message for f in r.unsuppressed)

    def test_clean_negative(self):
        r = run({"serving/t.py": TAXONOMY_NEG}, rules=["taxonomy-drift"])
        assert r.unsuppressed == []

    def test_skipped_without_terminal_reasons(self):
        r = run({"models/m.py": "def f():\n    return 1\n"},
                rules=["taxonomy-drift"])
        assert r.unsuppressed == []


# --------------------------------------------------------------------------
# 4. terminal-exactly-once
# --------------------------------------------------------------------------
TERMINAL_NEG = '''
class Engine:
    def _dispatch(self, batch, y):
        for req in batch:
            req.future.set_result(y)             # paired: accounted below
            self._finish_request(req.trace, "ok", tenant=req.tenant)
class GenerationHandle:
    def _fail(self, exc):
        self._req.future.set_exception(exc)      # the delivery primitive
        return True
class AdmissionController:
    def close(self):
        for req in list(self._q):
            req.future.set_exception(ValueError())  # hooks account
'''


class TestTerminalExactlyOnce:
    def test_reintroduce_raw_engine_set_exception(self):
        """Acceptance: a raw set_exception in an engine path with no
        accounting — the terminal would vanish from /api/slo and
        rejections_by_reason."""
        src = '''
class Engine:
    def _dispatch(self, batch, exc):
        for req in batch:
            req.future.set_exception(exc)
'''
        r = run({"serving/e.py": src}, rules=["terminal-exactly-once"])
        assert rules_hit(r) == {"terminal-exactly-once"}

    def test_raw_handle_fail(self):
        src = '''
class Engine:
    def _admit(self, req, exc):
        req.x.handle._fail(exc)
'''
        r = run({"serving/e.py": src}, rules=["terminal-exactly-once"])
        assert rules_hit(r) == {"terminal-exactly-once"}

    def test_clean_negative(self):
        r = run({"serving/e.py": TERMINAL_NEG},
                rules=["terminal-exactly-once"])
        assert r.unsuppressed == []


# --------------------------------------------------------------------------
# 5. recompile-risk
# --------------------------------------------------------------------------
RECOMPILE_NEG = '''
import numpy as np
class Engine:
    def prefill(self, prompt):
        bucket = self._bucket_for(prompt.size)   # ladder first
        padded = np.zeros((1, bucket), np.int32)
        return self._prefill(self.params, self._cache, padded)
'''


class TestRecompileRisk:
    def test_reintroduce_serving_layer_jit(self):
        """Acceptance: the exact defect this PR fixed in registry.py —
        an executable minted inside serving/."""
        src = '''
import jax
class Adapter:
    def infer(self, x):
        if self._fwd is None:
            self._fwd = jax.jit(lambda p, t: p @ t)
        return self._fwd(self.params, x)
'''
        r = run({"serving/registry.py": src}, rules=["recompile-risk"])
        assert rules_hit(r) == {"recompile-risk"}
        # the same code is legitimate inside a models/ factory home
        r2 = run({"models/factory.py": src}, rules=["recompile-risk"])
        assert r2.unsuppressed == []

    def test_pallas_call_is_executable_minting(self):
        """ISSUE 9: a Pallas kernel launch mints an executable exactly
        like jax.jit — a stray ``pl.pallas_call`` inside serving/ is
        flagged, while the sanctioned kernel-factory home (ops/, e.g.
        ``paged_decode_attention``) stays clean with no baseline entry."""
        src = '''
from jax.experimental import pallas as pl
class Engine:
    def attend(self, q, pool, tables):
        return pl.pallas_call(self._kern, grid=(q.shape[0],))(q, pool)
'''
        r = run({"serving/generation.py": src}, rules=["recompile-risk"])
        assert rules_hit(r) == {"recompile-risk"}
        assert any("pallas_call" in f.message for f in r.unsuppressed)
        r2 = run({"ops/pallas_kernels.py": src}, rules=["recompile-risk"])
        assert r2.unsuppressed == []

    def test_shape_bypassing_bucket_ladder(self):
        src = '''
import numpy as np
class Engine:
    def prefill(self, prompt):
        padded = np.zeros((1, prompt.size), np.int32)   # raw prompt len
        return self._prefill(self.params, self._cache, padded)
'''
        r = run({"serving/gen.py": src}, rules=["recompile-risk"])
        assert rules_hit(r) == {"recompile-risk"}
        assert any("fresh signature" in f.message for f in r.unsuppressed)

    def test_clean_negative(self):
        r = run({"serving/gen.py": RECOMPILE_NEG}, rules=["recompile-risk"])
        assert r.unsuppressed == []

    def test_nested_closure_reported_once_and_not_exempted_from_outside(self):
        """Review regression: a raw-shaped ctor inside a retry closure is
        ONE finding (not one per enclosing scope), and a bucket-helper
        call in the OUTER scope does not exempt the closure's own
        unrouted construction."""
        src = '''
import numpy as np
class Engine:
    def prefill(self, prompt):
        bucket = self._bucket_for(prompt.size)    # outer uses the ladder
        def attempt():
            padded = np.zeros((1, prompt.size), np.int32)   # closure: raw
            return self._prefill(self.params, self._cache, padded)
        return self._retry_call(attempt)
'''
        r = run({"serving/gen.py": src}, rules=["recompile-risk"])
        assert len(r.unsuppressed) == 1
        assert r.unsuppressed[0].func == "Engine.prefill.attempt"


# --------------------------------------------------------------------------
# transitive expansion (ISSUE 11): lock-discipline + donation-safety
# --------------------------------------------------------------------------
class TestTransitiveExpansion:
    def test_three_level_relock_chain(self):
        """One-level expansion (the PR 8 behavior) could not see this:
        the re-acquisition sits two calls below the held lock."""
        src = '''
class Engine:
    def outer(self):
        with self._lock:
            self.mid()
    def mid(self):
        self.leaf()
    def leaf(self):
        with self._lock:
            pass
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        msgs = [f.message for f in r.unsuppressed]
        assert any("re-acquires self._lock" in m
                   and "self.mid() -> self.leaf()" in m for m in msgs), msgs

    def test_three_level_order_inversion(self):
        src = '''
class Engine:
    def ab(self):
        with self._a_lock:
            self.mid()
    def mid(self):
        self.take_b()
    def take_b(self):
        with self._b_lock:
            pass
    def ba(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        assert any("inversion" in f.message for f in r.unsuppressed)

    def test_blocking_call_reached_through_chain(self):
        src = '''
import time
class Engine:
    def outer(self):
        with self._lock:
            self.mid()
    def mid(self):
        self.leaf()
    def leaf(self):
        time.sleep(0.1)
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        assert any("blocks (time.sleep)" in f.message
                   for f in r.unsuppressed)

    def test_cv_wait_through_chain_is_two_lock_sleep(self):
        """A helper's ``with self._cv: self._cv.wait()`` is exempt in
        ITS body (wait releases its own lock) but a caller holding a
        DIFFERENT lock across the call keeps that lock held for the
        whole wait — the two-lock sleep the direct form already flags
        must survive call indirection."""
        src = '''
class Engine:
    def drain(self):
        with self._wd_lock:
            self.await_quiesce()
    def await_quiesce(self):
        with self._cv:
            self._cv.wait()
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        assert any("waits on self._cv" in f.message
                   and "self._wd_lock" in f.message
                   for f in r.unsuppressed)
        # with no lock held at the caller, the same chain is clean
        clean = src.replace("        with self._wd_lock:\n"
                            "            self.await_quiesce()",
                            "        self.await_quiesce()")
        r2 = run({"serving/e.py": clean}, rules=["lock-discipline"])
        assert r2.unsuppressed == []

    def test_expansion_depth_is_bounded(self):
        """A chain deeper than EXPANSION_DEPTH is (deliberately) out of
        reach — the bound is what keeps the whole-package run inside
        the 10 s gate."""
        from tools.analysis.lock_discipline import LockDisciplineChecker
        chain = "\n".join(
            f"    def f{i}(self):\n        self.f{i + 1}()"
            for i in range(1, 8))
        src = ("class Engine:\n"
               "    def outer(self):\n"
               "        with self._lock:\n"
               "            self.f1()\n"
               + chain + "\n"
               "    def f8(self):\n"
               "        with self._lock:\n"
               "            pass\n")
        checker = LockDisciplineChecker()
        from tools.analysis.core import AnalysisUnit, SourceFile
        unit = AnalysisUnit([SourceFile("serving/e.py", src)])
        assert list(checker.check(unit)) == []        # 8 levels: out
        deep = LockDisciplineChecker(expansion_depth=16)
        assert any("re-acquires" in f.message
                   for f in deep.check(unit))          # raised bound: in

    def test_expansion_follows_inherited_methods(self):
        """The serving engines inherit their resilience scaffolding —
        the expansion must resolve ``self._retry_call()`` into the
        mixin even though it is another ClassDef."""
        src = '''
class Mixin:
    def _retry_call(self):
        with self._wd_lock:
            pass
class Engine(Mixin):
    def dispatch(self):
        with self._wd_lock:
            self._retry_call()
'''
        r = run({"serving/e.py": src}, rules=["lock-discipline"])
        assert any("re-acquires self._wd_lock" in f.message
                   for f in r.unsuppressed)

    def test_transitive_donation_through_helper(self):
        """A method that donates self._cache through a retry closure
        two calls down and never rebinds leaves the caller's read a
        use-after-donate."""
        src = '''
class Engine:
    def _fire(self):
        def call():
            return self._donated_call(
                "p", self._prefill, self.params, self._cache, self.row)
        return self._retry(call)
    def _step(self):
        self._fire()
    def scheduler(self):
        self._step()
        return self._cache["lengths"]
'''
        r = run({"serving/g.py": src}, rules=["donation-safety"])
        assert rules_hit(r) == {"donation-safety"}
        assert any("self._step" in f.message for f in r.unsuppressed)

    def test_writeback_method_does_not_propagate(self):
        """The scheduler shape every engine actually uses: the helper
        donates AND writes the fresh cache back — its callers see a
        live binding."""
        src = '''
class Engine:
    def _fire(self):
        out, toks = self._decode(self.params, self._cache, self.t)
        self._cache = out
        return toks
    def scheduler(self):
        self._fire()
        return self._cache["lengths"]
'''
        r = run({"serving/g.py": src}, rules=["donation-safety"])
        assert r.unsuppressed == []

    def test_epoch_guard_still_exempts_transitive_reads(self):
        src = '''
class Engine:
    def _fire(self):
        return self._decode(self.params, self._cache, self.t)
    def scheduler(self, epoch):
        self._fire()
        if self._epoch == epoch:
            return self._cache["lengths"]
'''
        r = run({"serving/g.py": src}, rules=["donation-safety"])
        assert r.unsuppressed == []


# --------------------------------------------------------------------------
# 6. wire-schema-drift (ISSUE 11)
# --------------------------------------------------------------------------
WIRE_NEG = '''
import dataclasses

@dataclasses.dataclass
class HostStatus:
    host_id: int
    queue_depth: int = 0
    seq: int = 0
    wire_version: int = 1

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        return cls(**kw)
'''


class TestWireSchemaDrift:
    def test_clean_negative(self):
        r = run({"serving/c.py": WIRE_NEG}, rules=["wire-schema-drift"])
        assert r.unsuppressed == []

    def test_missing_version_field(self):
        src = WIRE_NEG.replace("    wire_version: int = 1\n", "")
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        assert rules_hit(r) == {"wire-schema-drift"}
        assert any("version field" in f.message for f in r.unsuppressed)

    def test_reintroduce_heartbeat_seq_asymmetry(self):
        """Acceptance (the PR 10 class): a to_dict that hand-builds its
        payload and forgets ``seq`` — receivers would silently default
        it and the out-of-order heartbeat guard goes blind."""
        src = WIRE_NEG.replace(
            "        return dataclasses.asdict(self)",
            '        return {"host_id": self.host_id,\n'
            '                "queue_depth": self.queue_depth,\n'
            '                "wire_version": self.wire_version}')
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        assert any("never serializes field 'seq'" in f.message
                   for f in r.unsuppressed)

    def test_unknown_key_in_payload(self):
        # hand-built dict with a key that is not a field
        src2 = WIRE_NEG.replace(
            "        return dataclasses.asdict(self)",
            '        return {"host_id": self.host_id, "queue_depth": 0,\n'
            '                "seq": self.seq, "wire_version": 1,\n'
            '                "legacy_alias": self.host_id}')
        r = run({"serving/c.py": src2}, rules=["wire-schema-drift"])
        assert any("'legacy_alias'" in f.message and "not a declared"
                   in f.message for f in r.unsuppressed)

    def test_nested_payload_dicts_do_not_mask_or_fabricate(self):
        """Keys of dicts nested INSIDE the payload are content, not
        payload keys: a forgotten declared field must still flag even
        when a nested sub-dict happens to use its name, and the nested
        keys must not fire unknown-key findings."""
        src = WIRE_NEG.replace(
            "        return dataclasses.asdict(self)",
            '        return {"host_id": self.host_id,\n'
            '                "queue_depth": self.queue_depth,\n'
            '                "wire_version": self.wire_version,\n'
            '                "extras": {"seq": 0, "legacy": 1}}')
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        # the nested "seq" does NOT satisfy the declared seq field...
        assert any("never serializes field 'seq'" in f.message
                   for f in r.unsuppressed)
        # ...and nested keys are not "unknown field" false positives
        # ("extras" itself, a real top-level unknown, still flags)
        msgs = [f.message for f in r.unsuppressed]
        assert not any("'legacy'" in m for m in msgs)
        assert any("'extras'" in m and "not a declared" in m
                   for m in msgs)

    def test_raw_splat_is_unknown_field_intolerant(self):
        src = WIRE_NEG.replace(
            "        known = {f.name for f in dataclasses.fields(cls)}\n"
            "        kw = {k: v for k, v in d.items() if k in known}\n"
            "        return cls(**kw)",
            "        return cls(**d)")
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        assert any("splats the raw payload" in f.message
                   for f in r.unsuppressed)

    def test_explicit_ctor_must_read_required_fields(self):
        src = '''
import dataclasses

@dataclasses.dataclass
class Envelope:
    wire_version: int
    payload: str
    seq: int = 0

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(wire_version=d["wire_version"], seq=d.get("seq", 0))
'''
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        assert any("required field 'payload'" in f.message
                   for f in r.unsuppressed)

    def test_one_sided_report_payloads_are_skipped(self):
        """QosPolicy.to_dict has no from_dict — report-only payloads
        are not wire dataclasses."""
        src = '''
import dataclasses

@dataclasses.dataclass
class Report:
    a: int

    def to_dict(self):
        return {"a": self.a}
'''
        r = run({"serving/c.py": src}, rules=["wire-schema-drift"])
        assert r.unsuppressed == []

    def test_real_hoststatus_guard_armed(self):
        """Drift gate against the REAL cluster.py: stripping the
        wire_version field must fail the checker."""
        p = os.path.join(SERVING, "cluster.py")
        with open(p) as f:
            src = f.read()
        broken = src.replace("    wire_version: int = 2\n", "")
        assert broken != src
        r = run({p: broken}, rules=["wire-schema-drift"])
        assert any("version field" in f.message for f in r.unsuppressed)
        # and the live file is clean
        r2 = run({p: src}, rules=["wire-schema-drift"])
        assert r2.unsuppressed == []

    def test_speculative_stays_off_the_wire(self):
        """ISSUE 17 decision: speculative decoding is deployment-local
        config (registry.deploy(draft_model=...)), NOT a per-request
        knob — RpcRequest grows NO spec field, so v1 receivers need no
        defaulting story and the wire-schema-drift gate stays armed on
        an unchanged schema."""
        import dataclasses

        from deeplearning4j_tpu.serving import RpcRequest
        names = {f.name for f in dataclasses.fields(RpcRequest)}
        assert not any("spec" in n or "draft" in n for n in names), (
            "speculative config leaked into the wire schema — it is "
            "deployment-local by design (ISSUE 17 satellite)")
        # and the live rpc.py is clean under the drift rule
        p = os.path.join(SERVING, "rpc.py")
        with open(p) as f:
            src = f.read()
        r = run({p: src}, rules=["wire-schema-drift"])
        assert r.unsuppressed == []


# --------------------------------------------------------------------------
# 7. deadline-propagation (ISSUE 11)
# --------------------------------------------------------------------------
DEADLINE_NEG = '''
class ClusterFrontDoor:
    def submit(self, x, timeout_ms=None, tenant=None):
        h = self._pick()
        return h.submit_infer(x, timeout_ms=timeout_ms, tenant=tenant)
    def submit_derived(self, x, timeout_ms=None):
        tmo = timeout_ms if timeout_ms is not None else self.default
        return self._engine.submit(x, tmo)
    def submit_kwargs(self, prompt, **kwargs):
        return self._gen.submit(prompt, **kwargs)
    def no_deadline_here(self, req):
        return self._q.admit(req)     # deadline rides the Request object
    def remaining_budget(self, x, deadline_t):
        return self._h.submit(x, timeout_ms=(deadline_t - self._now()))
'''


class TestDeadlinePropagation:
    def test_clean_negative(self):
        r = run({"serving/fd.py": DEADLINE_NEG},
                rules=["deadline-propagation"])
        assert r.unsuppressed == []

    def test_dropped_deadline_on_forward(self):
        """Acceptance: the RPC-seam shape — a front door that accepts
        timeout_ms and forwards the request without it."""
        src = '''
class ClusterFrontDoor:
    def submit(self, x, timeout_ms=None, tenant=None):
        h = self._pick()
        return h.submit_infer(x, tenant=tenant)
'''
        r = run({"serving/fd.py": src}, rules=["deadline-propagation"])
        assert rules_hit(r) == {"deadline-propagation"}
        assert any("forwards without it" in f.message
                   for f in r.unsuppressed)

    def test_dropped_on_generate_and_admit(self):
        src = '''
class Host:
    def submit_generate(self, prompt, deadline_t=None):
        return self._gen.submit(prompt)
    def enqueue(self, req, timeout_ms=None):
        return self._q.admit(req)
'''
        r = run({"serving/h.py": src}, rules=["deadline-propagation"])
        assert len(r.unsuppressed) == 2

    def test_functions_without_deadline_params_are_exempt(self):
        src = '''
class Engine:
    def _drain(self):
        for req in self._backlog:
            self._q.admit(req)
'''
        r = run({"serving/e.py": src}, rules=["deadline-propagation"])
        assert r.unsuppressed == []

    def test_speculative_turn_covered(self):
        """ISSUE 17: the rule reaches the draft/verify turn shape — a
        host that accepts a deadline and dispatches the speculative leg
        without forwarding it must flag, and the REAL generation.py
        (where the spec turn lives inside the deadline-carrying decode
        scheduler) stays clean."""
        src = '''
class Host:
    def submit_speculative(self, prompt, timeout_ms=None):
        self._draft.submit(prompt)
        return self._verify.submit(prompt)
'''
        r = run({"serving/h.py": src}, rules=["deadline-propagation"])
        assert rules_hit(r) == {"deadline-propagation"}
        p = os.path.join(SERVING, "generation.py")
        with open(p) as f:
            live = f.read()
        assert "_spec_turn" in live      # the turn this test covers
        r2 = run({p: live}, rules=["deadline-propagation"])
        assert r2.unsuppressed == []


# --------------------------------------------------------------------------
# 8. metrics-drift (ISSUE 11)
# --------------------------------------------------------------------------
METRICS_NEG = '''
class Counter:
    pass

class ServingMetrics:
    def __init__(self):
        self.requests_total = Counter("requests_total")
        self.queue_depth = Gauge("queue_depth")
        self._lock = object()

    def record_rejection(self, reason):
        pass

    def counters(self):
        return {c.name: c.value for c in (self.requests_total,)}

    def snapshot(self):
        return {"queue_depth": self.queue_depth.value,
                "slo": {},
                **self.counters()}

class Engine:
    def _dispatch(self):
        self.metrics.requests_total.inc()
        self.metrics.record_rejection("x")

class Handler:
    def get(self):
        return self._metrics_rollup("slo")
'''


class TestMetricsDrift:
    def test_clean_negative(self):
        r = run({"serving/m.py": METRICS_NEG}, rules=["metrics-drift"])
        assert r.unsuppressed == []

    def test_typoed_reference(self):
        src = METRICS_NEG.replace("self.metrics.requests_total.inc()",
                                  "self.metrics.request_total.inc()")
        r = run({"serving/m.py": src}, rules=["metrics-drift"])
        assert any("request_total" in f.message and "does not exist"
                   in f.message for f in r.unsuppressed)

    def test_unexported_metric(self):
        src = METRICS_NEG.replace(
            'self.queue_depth = Gauge("queue_depth")',
            'self.queue_depth = Gauge("queue_depth")\n'
            '        self.orphan_total = Counter("orphan_total")')
        r = run({"serving/m.py": src}, rules=["metrics-drift"])
        assert any("orphan_total" in f.message and "never read"
                   in f.message for f in r.unsuppressed)

    def test_written_but_never_exported_metric_still_flags(self):
        """An engine inc'ing the metric is a RECORDING site, not an
        export — a counter that is written everywhere but surfaced by
        neither counters() nor snapshot() is exactly the
        recorded-cost-invisible-signal drift rule 2 exists for."""
        src = METRICS_NEG.replace(
            'self.queue_depth = Gauge("queue_depth")',
            'self.queue_depth = Gauge("queue_depth")\n'
            '        self.orphan_total = Counter("orphan_total")'
        ).replace(
            "self.metrics.requests_total.inc()",
            "self.metrics.requests_total.inc()\n"
            "        self.metrics.orphan_total.inc()")
        r = run({"serving/m.py": src}, rules=["metrics-drift"])
        assert any("orphan_total" in f.message and "never read"
                   in f.message for f in r.unsuppressed)
        # a genuine external READ (a bench sampling .value) does export
        src2 = src.replace(
            "        self.metrics.orphan_total.inc()",
            "        self.metrics.orphan_total.inc()\n"
            "        return self.metrics.orphan_total.value")
        r2 = run({"serving/m.py": src2}, rules=["metrics-drift"])
        assert not any("orphan_total" in f.message
                       for f in r2.unsuppressed)

    def test_declared_name_mismatch(self):
        src = METRICS_NEG.replace('Counter("requests_total")',
                                  'Counter("requests_totall")')
        r = run({"serving/m.py": src}, rules=["metrics-drift"])
        assert any("declared as" in f.message for f in r.unsuppressed)

    def test_endpoint_key_must_exist(self):
        src = METRICS_NEG.replace('self._metrics_rollup("slo")',
                                  'self._metrics_rollup("sloo")')
        r = run({"serving/m.py": src}, rules=["metrics-drift"])
        assert any("sloo" in f.message and "never emits" in f.message
                   for f in r.unsuppressed)

    def test_silent_without_servingmetrics(self):
        r = run({"models/m.py": "def f():\n    return 1\n"},
                rules=["metrics-drift"])
        assert r.unsuppressed == []

    def test_real_package_guard_armed(self):
        """Drift gates against the REAL tree: (a) dropping the "qos"
        key from metrics.snapshot() strands ui/server.py's
        _metrics_rollup("qos"); (b) typo'ing a recording site in
        resilience.py is caught."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                p = os.path.join(SERVING, name)
                with open(p) as f:
                    sources[p] = f.read()
        with open(UI_SERVER) as f:
            sources[UI_SERVER] = f.read()
        metrics_path = os.path.join(SERVING, "metrics.py")
        broken = dict(sources)
        removed = sources[metrics_path].replace(
            '"qos": self.qos_snapshot(),', "")
        assert removed != sources[metrics_path]
        broken[metrics_path] = removed
        r = analyze_sources(broken, rules=["metrics-drift"])
        assert any("'qos'" in f.message and "never emits" in f.message
                   for f in r.unsuppressed)
        broken = dict(sources)
        resilience_path = os.path.join(SERVING, "resilience.py")
        typoed = sources[resilience_path].replace(
            "self.metrics.retries_total", "self.metrics.retris_total", 1)
        assert typoed != sources[resilience_path]
        broken[resilience_path] = typoed
        r = analyze_sources(broken, rules=["metrics-drift"])
        assert any("retris_total" in f.message for f in r.unsuppressed)
        # the live tree is clean
        r2 = analyze_sources(sources, rules=["metrics-drift"])
        assert r2.unsuppressed == []

    def test_spec_counters_under_drift_gate(self):
        """ISSUE 17: the speculative counters ride the same drift gate —
        typo'ing the generation.py recording site of
        ``spec_fallbacks_total`` (the ONLY visibility a dead draft has
        under the DEGRADE contract) must flag, and stranding the
        snapshot's "spec" roll-up read by ui/server.py must flag."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                p = os.path.join(SERVING, name)
                with open(p) as f:
                    sources[p] = f.read()
        with open(UI_SERVER) as f:
            sources[UI_SERVER] = f.read()
        gen_path = os.path.join(SERVING, "generation.py")
        broken = dict(sources)
        typoed = sources[gen_path].replace(
            "self.metrics.spec_fallbacks_total",
            "self.metrics.spec_fallback_total", 1)
        assert typoed != sources[gen_path]
        broken[gen_path] = typoed
        r = analyze_sources(broken, rules=["metrics-drift"])
        assert any("spec_fallback_total" in f.message
                   for f in r.unsuppressed)
        metrics_path = os.path.join(SERVING, "metrics.py")
        broken = dict(sources)
        removed = sources[metrics_path].replace(
            '"spec": self.spec_snapshot(),', "")
        assert removed != sources[metrics_path]
        broken[metrics_path] = removed
        r = analyze_sources(broken, rules=["metrics-drift"])
        assert r.unsuppressed != []


# --------------------------------------------------------------------------
# 9. exception-chaining (ISSUE 11)
# --------------------------------------------------------------------------
CHAINING_NEG = '''
class Engine:
    def seat(self, refs):
        try:
            self._alloc.incref(refs)
        except ValueError as e:
            raise RuntimeError("prefix released; resubmit") from e
    def sever(self):
        try:
            self._probe()
        except OSError:
            raise TimeoutError("probe window closed") from None
    def reraise(self):
        try:
            self._go()
        except RuntimeError:
            raise
    def reraise_named(self):
        try:
            self._go()
        except RuntimeError as e:
            raise e
    def later(self):
        try:
            self._go()
        except RuntimeError:
            def fail():
                raise ValueError("runs outside the handler")
            return fail
'''


class TestExceptionChaining:
    def test_clean_negative(self):
        r = run({"serving/e.py": CHAINING_NEG},
                rules=["exception-chaining"])
        assert r.unsuppressed == []

    def test_lost_cause_flagged(self):
        src = CHAINING_NEG.replace(
            'raise RuntimeError("prefix released; resubmit") from e',
            'raise RuntimeError("prefix released; resubmit")')
        r = run({"serving/e.py": src}, rules=["exception-chaining"])
        assert rules_hit(r) == {"exception-chaining"}
        assert any("without 'from'" in f.message for f in r.unsuppressed)

    def test_reintroduce_generation_seating_shape(self):
        """Acceptance: the exact bug this PR fixed in generation.py —
        the incref-failure reraise dropped the allocator's cause."""
        p = os.path.join(SERVING, "generation.py")
        with open(p) as f:
            src = f.read()
        assert '"this request was being seated; resubmit") from e' in src
        broken = src.replace(
            '"this request was being seated; resubmit") from e',
            '"this request was being seated; resubmit")').replace(
            "except ValueError as e:", "except ValueError:", 1)
        r = run({p: broken}, rules=["exception-chaining"])
        assert rules_hit(r) == {"exception-chaining"}
        # and the live file is clean
        r2 = run({p: src}, rules=["exception-chaining"])
        assert r2.unsuppressed == []

    def test_nested_handler_scopes(self):
        src = '''
def f():
    try:
        g()
    except ValueError:
        try:
            h()
        except KeyError as k:
            raise RuntimeError("inner") from k
        raise RuntimeError("outer, unchained")
'''
        r = run({"serving/e.py": src}, rules=["exception-chaining"])
        assert len(r.unsuppressed) == 1
        assert r.unsuppressed[0].line == 10


# --------------------------------------------------------------------------
# suppressions + baseline
# --------------------------------------------------------------------------
class TestSuppressionsAndBaseline:
    SRC = '''
class Engine:
    def bad(self, req):
        with self._lock:
            req.future.result()   # analysis: ok lock-discipline — waived
    def bad2(self, req):
        with self._lock:
            # analysis: ok lock-discipline -- waived above the line
            x = req.future.result()
    def still_bad(self, req):
        with self._lock:
            req.future.result()   # analysis: ok donation-safety — wrong
'''

    def test_inline_suppression_same_line_and_above(self):
        r = run({"serving/s.py": self.SRC}, rules=["lock-discipline"])
        assert len(r.findings) == 3
        assert len(r.unsuppressed) == 1          # the wrong-rule waiver
        assert {f.line for f in r.suppressed} == {5, 9}
        assert all(f.suppression == "inline" and f.why
                   for f in r.suppressed)

    def test_multiline_justification_block(self):
        src = '''
class Engine:
    def bad(self, req):
        with self._lock:
            # analysis: ok lock-discipline — the justification for this
            # waiver continues over several comment lines, which must
            # still attach to the finding directly below the block
            req.future.result()
'''
        r = run({"serving/s.py": src}, rules=["lock-discipline"])
        assert r.unsuppressed == [] and len(r.suppressed) == 1

    def test_baseline_round_trip(self, tmp_path):
        r = run({"serving/s.py": self.SRC}, rules=["lock-discipline"])
        bp = tmp_path / "baseline.json"
        n = Baseline.write(str(bp), r.findings, why="grandfathered")
        assert n == 1                            # only the unsuppressed one
        bl = Baseline.load(str(bp))
        r2 = run({"serving/s.py": self.SRC}, rules=["lock-discipline"],
                 baseline=bl)
        assert r2.unsuppressed == []
        assert {f.suppression for f in r2.suppressed} == {"inline",
                                                          "baseline"}

    def test_baseline_invalidates_when_the_line_changes(self, tmp_path):
        r = run({"serving/s.py": self.SRC}, rules=["lock-discipline"])
        bp = tmp_path / "baseline.json"
        Baseline.write(str(bp), r.findings)
        changed = self.SRC.replace("req.future.result()   # analysis: ok "
                                   "donation-safety — wrong",
                                   "req.other_future.result()")
        r2 = run({"serving/s.py": changed}, rules=["lock-discipline"],
                 baseline=Baseline.load(str(bp)))
        assert len(r2.unsuppressed) == 1         # edited site resurfaces

    def test_fingerprints_distinguish_same_named_files(self):
        """Review regression: fingerprints key on parent-dir + basename,
        so the same finding in serving/e.py and models/e.py must NOT
        collide (a waiver for one would silently suppress the other)."""
        src = ("class E:\n    def f(self, req):\n"
               "        with self._lock:\n"
               "            req.future.result()\n")
        r = run({"serving/e.py": src, "models/e.py": src},
                rules=["lock-discipline"])
        fps = {f.fingerprint() for f in r.findings}
        assert len(r.findings) == 2 and len(fps) == 2
        # and stable across absolute vs relative spellings of one tree
        r2 = run({"/abs/prefix/serving/e.py": src},
                 rules=["lock-discipline"])
        assert r2.findings[0].fingerprint() in fps

    def test_baseline_entry_waives_one_occurrence_only(self, tmp_path):
        """Review regression: a waiver for one occurrence of a line must
        not suppress a LATER duplicate of the same line in the same
        function — that duplicate is a new, unreviewed finding."""
        one = '''
class Engine:
    def f(self, req):
        with self._lock:
            req.future.result()
'''
        r = run({"serving/e.py": one}, rules=["lock-discipline"])
        bp = tmp_path / "bl.json"
        Baseline.write(str(bp), r.findings)
        two = '''
class Engine:
    def f(self, req):
        with self._lock:
            req.future.result()
        with self._lock:
            req.future.result()
'''
        r2 = run({"serving/e.py": two}, rules=["lock-discipline"],
                 baseline=Baseline.load(str(bp)))
        assert len(r2.findings) == 2
        assert len(r2.unsuppressed) == 1     # only ONE occurrence waived

    def test_baseline_survives_line_drift(self, tmp_path):
        """Fingerprints are content-based: code inserted ABOVE a
        baselined site must not resurrect it."""
        r = run({"serving/s.py": self.SRC}, rules=["lock-discipline"])
        bp = tmp_path / "baseline.json"
        Baseline.write(str(bp), r.findings)
        drifted = "import time\n\n\n" + self.SRC
        r2 = run({"serving/s.py": drifted}, rules=["lock-discipline"],
                 baseline=Baseline.load(str(bp)))
        assert r2.unsuppressed == []


# --------------------------------------------------------------------------
# the real-package gate
# --------------------------------------------------------------------------
class TestRealPackageGate:
    @pytest.fixture(scope="class")
    def gate_report(self):
        """ONE full-scope run shared by the gate assertions (the run
        itself is what the speed gate times)."""
        return analyze_paths(GATE_SCOPE,
                             baseline=Baseline.load(DEFAULT_BASELINE))

    def test_zero_unsuppressed_findings(self, gate_report):
        """THE acceptance gate: the analyzer over serving/ + models/ +
        ops/ + tools/ + ui/server.py reports zero unsuppressed findings
        with all nine checkers and the transitive expansion on — every
        true positive is either fixed or carries a written
        justification."""
        report = gate_report
        assert report.errors == []
        assert report.files_analyzed >= 30
        pretty = "\n".join(f"{f.location()}: {f.rule}: {f.message}"
                           for f in report.unsuppressed)
        assert report.unsuppressed == [], f"unsuppressed findings:\n{pretty}"
        # the waived sites are visible, justified, and few
        assert 1 <= len(report.suppressed) <= 24
        assert all(f.why for f in report.suppressed)

    def test_fast_enough_for_tier1(self, gate_report):
        """CI satellite: the whole-package run stays under the existing
        10 s speed gate WITH the ISSUE 11 checkers + transitive
        expansion on, over the broadened scope."""
        assert gate_report.elapsed_s < 10.0

    def test_every_checker_ran(self):
        report = analyze_paths([SERVING, MODELS])
        assert set(report.rules) == RULES == {
            "lock-discipline", "donation-safety", "taxonomy-drift",
            "terminal-exactly-once", "recompile-risk",
            "wire-schema-drift", "deadline-propagation", "metrics-drift",
            "exception-chaining"}

    def test_no_new_pytest_markers(self):
        """ISSUE 11 satellite (amended by ISSUE 18's ``soak`` marker for
        the fleet chaos soak tier): pytest.ini's marker set must not
        grow past this explicit list."""
        cp = configparser.ConfigParser()
        cp.read(REPO / "pytest.ini")
        names = {line.strip().split(":")[0]
                 for line in cp["pytest"]["markers"].splitlines()
                 if line.strip()}
        assert names == {"slow", "stress", "chaos", "analysis", "soak"}

    def test_taxonomy_checker_sees_real_terminal_reasons(self):
        """The generalized drift guard is actually armed: dropping a
        known reason from the real tracing.py TERMINAL_REASONS (in
        memory) must produce taxonomy findings."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                p = os.path.join(SERVING, name)
                with open(p) as f:
                    sources[p] = f.read()
        tracing_path = os.path.join(SERVING, "tracing.py")
        broken = sources[tracing_path].replace('"kv_blocks_exhausted",', "")
        assert broken != sources[tracing_path]
        sources[tracing_path] = broken
        r = analyze_sources(sources, rules=["taxonomy-drift"])
        assert any("kv_blocks_exhausted" in f.message
                   for f in r.unsuppressed)


# --------------------------------------------------------------------------
# Pod-slice control plane (ISSUE 10 satellite): serving/cluster.py rides
# the same gate, and the lock-discipline checker sees the directory's
# heartbeat lock
# --------------------------------------------------------------------------
CLUSTER_HB_TP = '''
class ClusterDirectory:
    def heartbeat_blocking(self, status, fut):
        with self._hb_lock:                      # the directory's lock
            fut.result()                         # blocking under it: bug
    def probe_then_dispatch(self, h, x):
        with self._hb_lock:
            h.infer(x)                           # device call under it: bug
'''

CLUSTER_HB_NEG = '''
class ClusterDirectory:
    def heartbeat(self, status):
        hid = int(status.host_id)
        with self._hb_lock:                      # bookkeeping only: fine
            self._status[hid] = status
            self._seen_at[hid] = self._clock()
    def api_snapshot(self):
        with self._hb_lock:
            hosts = dict(self._status)
        return hosts                             # heavy work outside
'''


class TestClusterGate:
    def test_cluster_module_zero_unsuppressed(self):
        """serving/cluster.py is inside the package gate already (it
        lives in serving/); this pins the satellite explicitly — the new
        control plane alone analyzes clean under every checker."""
        target = os.path.join(SERVING, "cluster.py")
        assert os.path.exists(target)
        report = analyze_paths([target],
                               baseline=Baseline.load(DEFAULT_BASELINE))
        assert report.errors == []
        assert report.files_analyzed == 1
        pretty = "\n".join(f"{f.location()}: {f.rule}: {f.message}"
                           for f in report.unsuppressed)
        assert report.unsuppressed == [], pretty

    def test_heartbeat_lock_checker_armed(self):
        """Fixture proof: blocking calls under a directory-heartbeat
        lock (``self._hb_lock``) are exactly what the lock-discipline
        checker flags — the shape the control plane must never grow."""
        r = run({"serving/cluster.py": CLUSTER_HB_TP},
                rules=["lock-discipline"])
        msgs = [f.message for f in r.unsuppressed]
        assert any("_hb_lock" in m and ".result()" in m for m in msgs), msgs
        assert any("_hb_lock" in m and "infer" in m for m in msgs), msgs

    def test_heartbeat_bookkeeping_clean(self):
        r = run({"serving/cluster.py": CLUSTER_HB_NEG},
                rules=["lock-discipline"])
        assert r.unsuppressed == []

    def test_cluster_terminal_reasons_registered(self):
        """Drift guard armed against the REAL tracing.py: dropping
        either new cluster reason from TERMINAL_REASONS must fail the
        taxonomy checker (the admission-side typed errors still carry
        them)."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                p = os.path.join(SERVING, name)
                with open(p) as f:
                    sources[p] = f.read()
        tracing_path = os.path.join(SERVING, "tracing.py")
        for reason in ("cluster_capacity", "host_unavailable"):
            broken = dict(sources)
            removed = sources[tracing_path].replace(f'"{reason}",', "")
            assert removed != sources[tracing_path]
            broken[tracing_path] = removed
            r = analyze_sources(broken, rules=["taxonomy-drift"])
            assert any(reason in f.message for f in r.unsuppressed), reason


# --------------------------------------------------------------------------
# ISSUE 12 gate: the RPC data plane's wire + deadline contracts
# --------------------------------------------------------------------------
class TestRpcGate:
    def _rpc_source(self):
        p = os.path.join(SERVING, "rpc.py")
        with open(p) as f:
            return p, f.read()

    def test_rpc_module_zero_unsuppressed(self):
        """serving/rpc.py is inside the package gate already; this pins
        the satellite explicitly — the data plane alone analyzes clean
        under every checker (the deadline-propagation rule covering the
        new submit surface included)."""
        p, _ = self._rpc_source()
        report = analyze_paths([p], baseline=Baseline.load(DEFAULT_BASELINE))
        assert report.errors == []
        assert report.files_analyzed == 1
        pretty = "\n".join(f"{f.location()}: {f.rule}: {f.message}"
                           for f in report.unsuppressed)
        assert report.unsuppressed == [], pretty

    def test_wire_version_guard_armed_for_rpc_request(self):
        """Reintroduction gate against the REAL rpc.py: stripping the
        RPC request schema's wire_version field must fail the
        wire-schema-drift checker (exactly the HostStatus gate's shape,
        extended to the data plane)."""
        p, src = self._rpc_source()
        broken = src.replace(
            "    hedge_attempt: int = 0\n    wire_version: int = 3\n",
            "    hedge_attempt: int = 0\n")
        assert broken != src
        r = run({p: broken}, rules=["wire-schema-drift"])
        assert any("RpcRequest" in f.message and "version field"
                   in f.message for f in r.unsuppressed)

    def test_raw_splat_guard_armed_for_rpc_request(self):
        """A from_dict that splats the raw payload (``cls(**d)``) would
        crash on a newer peer's unknown field mid-rolling-upgrade —
        reintroducing it in the real rpc.py must fail the checker."""
        p, src = self._rpc_source()
        broken = src.replace(
            "        known = {f.name for f in dataclasses.fields(cls)}\n"
            "        return cls(**{k: v for k, v in d.items() "
            "if k in known})",
            "        return cls(**d)", 1)
        assert broken != src
        r = run({p: broken}, rules=["wire-schema-drift"])
        assert any("splats the raw payload" in f.message
                   for f in r.unsuppressed)

    def test_deadline_guard_armed_for_rpc_submit_surface(self):
        """Acceptance: the deadline-propagation checker covers the RPC
        submit surface — the server-side ``_submit`` dropping the
        arrived budget on its engine forward must flag."""
        p, src = self._rpc_source()
        broken = src.replace(
            "                fut = self.host.submit_infer(\n"
            "                    arr, timeout_ms=timeout_ms, "
            "tenant=req.tenant,\n",
            "                fut = self.host.submit_infer(\n"
            "                    arr, tenant=req.tenant,\n")
        assert broken != src
        r = run({p: broken}, rules=["deadline-propagation"])
        assert any("forwards without it" in f.message
                   for f in r.unsuppressed)

    def test_rpc_terminal_reasons_registered(self):
        """Drift guard armed against the REAL tracing.py for the two
        new data-plane reasons."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                q = os.path.join(SERVING, name)
                with open(q) as f:
                    sources[q] = f.read()
        tracing_path = os.path.join(SERVING, "tracing.py")
        for reason in ("host_draining", "rpc_error"):
            broken = dict(sources)
            removed = sources[tracing_path].replace(f'"{reason}",', "")
            assert removed != sources[tracing_path]
            broken[tracing_path] = removed
            r = analyze_sources(broken, rules=["taxonomy-drift"])
            assert any(reason in f.message for f in r.unsuppressed), reason


# --------------------------------------------------------------------------
# KV occupancy gate (ISSUE 13): the 'preempted' terminal rides the same
# taxonomy discipline as every other typed shed
# --------------------------------------------------------------------------
class TestKvOccupancyGate:
    def test_preempted_reason_drift_guard_armed(self):
        """Reintroduction gate against the REAL tracing.py: dropping
        'preempted' from TERMINAL_REASONS (in memory) while
        admission.PreemptedError still sheds it must produce taxonomy
        findings — the preemption path's typed terminal cannot silently
        leave the one vocabulary."""
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                q = os.path.join(SERVING, name)
                with open(q) as f:
                    sources[q] = f.read()
        tracing_path = os.path.join(SERVING, "tracing.py")
        removed = sources[tracing_path].replace('"preempted",', "")
        assert removed != sources[tracing_path]
        broken = dict(sources)
        broken[tracing_path] = removed
        r = analyze_sources(broken, rules=["taxonomy-drift"])
        assert any("preempted" in f.message for f in r.unsuppressed)
        # and the live tree is clean
        clean = analyze_sources(sources, rules=["taxonomy-drift"])
        assert [f for f in clean.unsuppressed
                if "preempted" in f.message] == []


# --------------------------------------------------------------------------
# ISSUE 15 gate: resume-from-watermark wire fields + the swap path's
# no-new-terminal discipline
# --------------------------------------------------------------------------
class TestStreamRecoveryGate:
    def _rpc_source(self):
        p = os.path.join(SERVING, "rpc.py")
        with open(p) as f:
            return p, f.read()

    def test_resume_fields_ride_wire_v2(self):
        """Source pin: the resume fields and the v2 bump live on BOTH
        envelopes — the request carries ``resume_tokens``/``resume_step``
        and the response echoes the honored ``resume_step`` — while the
        chunk schema stays v1 (untouched by the resume change). A revert
        to v1 defaults would silently turn every re-dispatch back into a
        full replay. ISSUE 19 bumped the request to v3 (trace context)
        and the kv.migrate request to v2 — the resume fields ride along
        unchanged."""
        _, src = self._rpc_source()
        assert "resume_tokens: Optional[list] = None" in src
        assert src.count("\n    resume_step: int = 0") == 2
        # request @ v3 (trace context), response @ v2 (resume echo) +
        # kv.migrate request @ v2 (trace context)
        assert src.count("    wire_version: int = 3\n") == 1
        assert src.count("    wire_version: int = 2\n") == 2
        # the chunk plus the kv.migrate response stay v1
        assert src.count("    wire_version: int = 1\n") == 2
        assert "class KvMigrateRequest" in src
        assert "class KvMigrateResponse" in src

    def test_resume_serialization_guard_armed(self):
        """Reintroduction gate (the PR 10 asymmetry class extended to
        the resume fields): a hand-built RpcRequest.to_dict that forgets
        them must fail wire-schema-drift — the receiving host would
        default resume_step to 0 and the 'resumed' stream would
        re-prefill and re-decode from scratch."""
        p, src = self._rpc_source()
        broken = src.replace(
            "    def to_dict(self) -> dict:\n"
            "        return dataclasses.asdict(self)",
            '    def to_dict(self) -> dict:\n'
            '        return {"request_id": self.request_id,\n'
            '                "kind": self.kind,\n'
            '                "prompt": self.prompt,\n'
            '                "wire_version": self.wire_version}',
            1)
        assert broken != src
        r = run({p: broken}, rules=["wire-schema-drift"])
        msgs = [f.message for f in r.unsuppressed]
        assert any("RpcRequest" in m and "'resume_tokens'" in m
                   and "never serializes" in m for m in msgs), msgs
        assert any("RpcRequest" in m and "'resume_step'" in m
                   for m in msgs)

    def test_swap_path_adds_no_terminal_reason(self):
        """The swap contract: ``kv.swap_out``/``kv.swap_in`` failures
        DEGRADE to the recompute path — they never shed a stream, so
        the one taxonomy must not have grown a swap reason. And the
        tempting-but-wrong design (a typed swap shed) stays gated: an
        unregistered KvSwapFailedError must fail the taxonomy checker."""
        tracing_path = os.path.join(SERVING, "tracing.py")
        with open(tracing_path) as f:
            tsrc = f.read()
        taxonomy = tsrc.split("TERMINAL_REASONS")[1].split(")")[0]
        assert "swap" not in taxonomy
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                q = os.path.join(SERVING, name)
                with open(q) as f:
                    sources[q] = f.read()
        adm = os.path.join(SERVING, "admission.py")
        broken = dict(sources)
        broken[adm] = sources[adm] + '''

class KvSwapFailedError(RejectedError):
    def __init__(self, msg):
        super().__init__(msg, "kv_swap_failed")
'''
        r = analyze_sources(broken, rules=["taxonomy-drift"])
        assert any("KvSwapFailedError" in f.message
                   for f in r.unsuppressed)
        # and the live tree is clean of any swap-flavored drift
        clean = analyze_sources(sources, rules=["taxonomy-drift"])
        assert [f for f in clean.unsuppressed
                if "swap" in f.message.lower()] == []


# --------------------------------------------------------------------------
# ISSUE 16 gate: the kv.migrate wire schema, deadline flow through the
# two-stage disaggregated dispatch, and the migrate path's
# no-new-terminal discipline
# --------------------------------------------------------------------------
class TestDisaggGate:
    def _source(self, name):
        p = os.path.join(SERVING, name)
        with open(p) as f:
            return p, f.read()

    def _serving_sources(self):
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                q = os.path.join(SERVING, name)
                with open(q) as f:
                    sources[q] = f.read()
        return sources

    def test_disagg_module_zero_unsuppressed(self):
        """serving/disagg.py analyzes clean under every checker — the
        whole two-stage placement path, no baseline entries."""
        p, _ = self._source("disagg.py")
        report = analyze_paths([p], baseline=Baseline.load(DEFAULT_BASELINE))
        assert report.errors == []
        pretty = "\n".join(f"{f.location()}: {f.rule}: {f.message}"
                           for f in report.unsuppressed)
        assert report.unsuppressed == [], pretty

    def test_migrate_schema_guard_armed(self):
        """wire-schema-drift covers the kv.migrate dataclasses: a
        hand-built KvMigrateRequest.to_dict that forgets the page
        payload must flag — the decode host would seat zero pages and
        silently recompute every migrated stream."""
        p, src = self._source("rpc.py")
        anchor = (
            "    wire_version: int = 2\n"
            "\n"
            "    def to_dict(self) -> dict:\n"
            "        return dataclasses.asdict(self)\n"
            "\n"
            "    @classmethod\n"
            "    def from_dict(cls, d: dict) -> \"KvMigrateRequest\":")
        broken = src.replace(
            anchor,
            anchor.replace(
                "        return dataclasses.asdict(self)",
                '        return {"request_id": self.request_id,\n'
                '                "kind": self.kind,\n'
                '                "prompt": self.prompt,\n'
                '                "wire_version": self.wire_version}'),
            1)
        assert broken != src
        r = run({p: broken}, rules=["wire-schema-drift"])
        msgs = [f.message for f in r.unsuppressed]
        assert any("KvMigrateRequest" in m and "'pages'" in m
                   and "never serializes" in m for m in msgs), msgs
        assert any("KvMigrateRequest" in m and "'block_size'" in m
                   for m in msgs)

    def test_deadline_guard_armed_for_two_stage_dispatch(self):
        """deadline-propagation covers BOTH dispatch stages: dropping
        the shrinking budget from the stage-B decode forward must flag
        — a migrated stream would decode against an unbounded wait
        while the caller's 50 ms budget expired at stage A."""
        p, src = self._source("disagg.py")
        broken = src.replace(
            "            h2 = hb.submit_generate(\n"
            "                toks, max_new_tokens=max_new_tokens,\n"
            "                timeout_ms=deadline_budget(), tenant=tenant,\n",
            "            h2 = hb.submit_generate(\n"
            "                toks, max_new_tokens=max_new_tokens,\n"
            "                tenant=tenant,\n", 1)
        assert broken != src
        r = run({p: broken}, rules=["deadline-propagation"])
        assert any("forwards without it" in f.message
                   for f in r.unsuppressed)
        # ... and the stage-A migrate hop rides the same rule
        broken_a = src.replace(
            "                pf = ha.migrate_prefill(\n"
            "                    toks, max_new_tokens=max_new_tokens,\n"
            "                    timeout_ms=deadline_budget(), "
            "tenant=tenant,\n",
            "                pf = ha.migrate_prefill(\n"
            "                    toks, max_new_tokens=max_new_tokens,\n"
            "                    tenant=tenant,\n", 1)
        assert broken_a != src
        r2 = run({p: broken_a}, rules=["deadline-propagation"])
        assert any("forwards without it" in f.message
                   for f in r2.unsuppressed)

    def test_migrate_path_adds_no_terminal_reason(self):
        """The migrate contract mirrors the swap contract: kv.migrate
        failures DEGRADE to recompute on the decode host, never shed —
        the one taxonomy must not grow a migrate reason, and the
        tempting-but-wrong typed shed stays gated."""
        tracing_path = os.path.join(SERVING, "tracing.py")
        with open(tracing_path) as f:
            tsrc = f.read()
        taxonomy = tsrc.split("TERMINAL_REASONS")[1].split(")")[0]
        assert "migrate" not in taxonomy
        sources = self._serving_sources()
        adm = os.path.join(SERVING, "admission.py")
        broken = dict(sources)
        broken[adm] = sources[adm] + '''

class KvMigrateFailedError(RejectedError):
    def __init__(self, msg):
        super().__init__(msg, "migrate_failed")
'''
        r = analyze_sources(broken, rules=["taxonomy-drift"])
        assert any("KvMigrateFailedError" in f.message
                   for f in r.unsuppressed)
        # and the live tree carries no migrate-flavored drift
        clean = analyze_sources(sources, rules=["taxonomy-drift"])
        assert [f for f in clean.unsuppressed
                if "migrate" in f.message.lower()] == []


# --------------------------------------------------------------------------
# Fleet chaos soak (ISSUE 18 satellite): the load/chaos/ledger modules
# ride the same gate, and the ledger adds no terminal vocabulary
# --------------------------------------------------------------------------
class TestSoakGate:
    SOAK_FILES = (
        os.path.join(SERVING, "loadgen.py"),
        os.path.join(SERVING, "ledger.py"),
        os.path.join(TOOLS, "soak.py"),
    )

    def test_soak_modules_zero_unsuppressed(self):
        """serving/loadgen.py, serving/ledger.py and tools/soak.py
        analyze clean under every checker — the whole harness, no new
        baseline entries."""
        for p in self.SOAK_FILES:
            assert os.path.exists(p), p
        report = analyze_paths(list(self.SOAK_FILES),
                               baseline=Baseline.load(DEFAULT_BASELINE))
        assert report.errors == []
        pretty = "\n".join(f"{f.location()}: {f.rule}: {f.message}"
                           for f in report.unsuppressed)
        assert report.unsuppressed == [], pretty

    def test_ledger_adds_no_terminal_reasons(self):
        """The ledger reports leaks as dimension strings, never as
        typed request terminals: neither ledger.py nor loadgen.py may
        add entries to tracing.TERMINAL_REASONS, and the taxonomy
        checker over serving/ stays clean with them in scope (loadgen's
        'stuck' / 'pending' are report labels, not shed reasons)."""
        from deeplearning4j_tpu.serving.tracing import TERMINAL_REASONS

        assert "stuck" not in TERMINAL_REASONS
        assert "pending" not in TERMINAL_REASONS
        sources = {}
        for name in os.listdir(SERVING):
            if name.endswith(".py"):
                q = os.path.join(SERVING, name)
                with open(q) as f:
                    sources[q] = f.read()
        r = analyze_sources(sources, rules=["taxonomy-drift"])
        assert [f for f in r.unsuppressed
                if "ledger" in f.path or "loadgen" in f.path] == []


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------
class TestCli:
    def _run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.analysis", *args],
            capture_output=True, text=True, cwd=str(REPO), timeout=120)

    def test_json_mode_clean_exit(self):
        """bench/CI contract: --json emits a parsable report and the
        real package (full ISSUE 11 scope) exits 0. The v2 schema
        carries schema_version; the v1 key set is otherwise intact."""
        p = self._run_cli(*GATE_SCOPE, "--json")
        assert p.returncode == 0, p.stdout + p.stderr
        d = json.loads(p.stdout)
        assert d["schema_version"] == 2
        assert d["counts"]["unsuppressed"] == 0
        assert d["counts"]["suppressed"] >= 1
        assert d["files_analyzed"] >= 30
        assert set(d["rules"]) == RULES
        for v1_key in ("files_analyzed", "elapsed_s", "rules", "counts",
                       "errors", "findings"):
            assert v1_key in d

    def test_findings_exit_nonzero(self, tmp_path):
        bad = tmp_path / "serving"
        bad.mkdir()
        (bad / "e.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        p = self._run_cli(str(bad), "--no-baseline", "--json")
        assert p.returncode == 1
        d = json.loads(p.stdout)
        assert d["counts"]["by_rule"].get("lock-discipline") == 1

    def test_rule_filter_and_usage_errors(self, tmp_path):
        p = self._run_cli(str(tmp_path), "--rules", "no-such-rule")
        assert p.returncode == 2
        p = self._run_cli(str(tmp_path / "missing"))
        assert p.returncode == 2
        p = self._run_cli(str(tmp_path), "--prune-baseline")
        assert p.returncode == 2   # prune without write is a misuse
        p = self._run_cli("--list-rules", "x")
        assert p.returncode == 0
        for rule in RULES:
            assert rule in p.stdout

    def test_write_baseline_round_trip(self, tmp_path):
        bad = tmp_path / "serving"
        bad.mkdir()
        (bad / "e.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        bp = tmp_path / "bl.json"
        p = self._run_cli(str(bad), "--baseline", str(bp),
                          "--write-baseline")
        assert p.returncode == 0 and "baselined 1" in p.stdout
        p = self._run_cli(str(bad), "--baseline", str(bp))
        assert p.returncode == 0, p.stdout

    def test_rewrite_baseline_preserves_entries_and_whys(self, tmp_path):
        """Review regression: re-running --write-baseline must MERGE
        with the loaded baseline, not wipe the already-waived findings
        (and their hand-written justifications) because they now report
        as suppressed."""
        bad = tmp_path / "serving"
        bad.mkdir()
        (bad / "e.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        bp = tmp_path / "bl.json"
        self._run_cli(str(bad), "--baseline", str(bp), "--write-baseline")
        d = json.loads(bp.read_text())
        d["findings"][0]["why"] = "hand-written justification"
        bp.write_text(json.dumps(d))
        p = self._run_cli(str(bad), "--baseline", str(bp),
                          "--write-baseline")
        assert p.returncode == 0 and "baselined 1" in p.stdout, p.stdout
        d2 = json.loads(bp.read_text())
        assert len(d2["findings"]) == 1
        assert d2["findings"][0]["why"] == "hand-written justification"
        p = self._run_cli(str(bad), "--baseline", str(bp))
        assert p.returncode == 0, p.stdout

    def test_narrowed_scope_keeps_out_of_scope_waivers(self, tmp_path):
        """Review regression: --write-baseline from a run narrowed by
        --rules (or a path subset) must keep waivers that did not fire
        in that run — only --prune-baseline garbage-collects."""
        bad = tmp_path / "serving"
        bad.mkdir()
        (bad / "e.py").write_text(
            "import jax\nclass E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n"
            "    def g(self):\n"
            "        return jax.jit(lambda x: x)\n")
        bp = tmp_path / "bl.json"
        self._run_cli(str(bad), "--baseline", str(bp), "--write-baseline")
        assert len(json.loads(bp.read_text())["findings"]) == 2
        # a rules-narrowed rewrite must not drop the other rule's waiver
        p = self._run_cli(str(bad), "--baseline", str(bp),
                          "--rules", "lock-discipline", "--write-baseline")
        assert p.returncode == 0
        entries = json.loads(bp.read_text())["findings"]
        assert {e["rule"] for e in entries} == {"lock-discipline",
                                               "recompile-risk"}
        # full-scope prune drops a waiver whose code was fixed
        (bad / "e.py").write_text(
            "import jax\nclass E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        p = self._run_cli(str(bad), "--baseline", str(bp),
                          "--write-baseline", "--prune-baseline")
        assert p.returncode == 0
        entries = json.loads(bp.read_text())["findings"]
        assert {e["rule"] for e in entries} == {"lock-discipline"}

    def test_paths_with_no_py_files_are_usage_errors(self, tmp_path):
        """Review regression: an existing path contributing no .py files
        must exit 2, not report a clean '0 files analyzed' green."""
        (tmp_path / "README.md").write_text("hi\n")
        p = self._run_cli(str(tmp_path / "README.md"))
        assert p.returncode == 2 and "no .py files" in p.stderr
        empty = tmp_path / "renamed_dir"
        empty.mkdir()
        p = self._run_cli(str(empty))
        assert p.returncode == 2

    def _git(self, cwd, *args):
        p = subprocess.run(["git", *args], capture_output=True, text=True,
                           cwd=str(cwd), timeout=60)
        assert p.returncode == 0, p.stderr
        return p.stdout

    @pytest.fixture
    def git_repo(self, tmp_path):
        """A throwaway repo with one clean committed serving file."""
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "config", "user.email", "t@t")
        self._git(tmp_path, "config", "user.name", "t")
        serving = tmp_path / "serving"
        serving.mkdir()
        (serving / "clean.py").write_text(
            "class E:\n    def f(self):\n        return 1\n")
        (serving / "untouched.py").write_text(
            "import jax\n"
            "def mint():\n    return jax.jit(lambda x: x)\n")
        self._git(tmp_path, "add", "-A")
        # the committed tree already carries a finding in untouched.py —
        # --changed-only must NOT see it unless the file changes
        self._git(tmp_path, "-c", "commit.gpgsign=false", "commit",
                  "-q", "-m", "seed")
        return tmp_path

    def _run_cli_in(self, cwd, *args):
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        return subprocess.run(
            [sys.executable, "-m", "tools.analysis", *args],
            capture_output=True, text=True, cwd=str(cwd), env=env,
            timeout=120)

    def test_changed_only_no_py_changes_is_clean(self, git_repo):
        """ISSUE 11 satellite: the pre-commit fast path — nothing
        changed vs HEAD exits 0 WITHOUT the no-.py-files usage error
        explicit paths get."""
        p = self._run_cli_in(git_repo, "--changed-only", "--no-baseline")
        assert p.returncode == 0, p.stdout + p.stderr
        assert "clean" in p.stdout

    def test_changed_only_sees_only_the_diff(self, git_repo):
        """A new finding in a CHANGED file fails; the pre-existing
        finding in the untouched file stays out of scope (that is the
        whole-package gate's job, not the pre-commit path's)."""
        (git_repo / "serving" / "clean.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        p = self._run_cli_in(git_repo, "--changed-only", "--no-baseline",
                             "--json")
        assert p.returncode == 1
        d = json.loads(p.stdout)
        assert d["schema_version"] == 2   # schema unchanged by the mode
        assert d["files_analyzed"] == 1
        assert d["counts"]["by_rule"] == {"lock-discipline": 1}
        paths = {f["path"] for f in d["findings"]}
        assert all(p.endswith("clean.py") for p in paths)

    def test_changed_only_sees_untracked_files(self, git_repo):
        """A brand-new un-added file is exactly the pre-commit surface
        most likely to carry fresh findings — ``git diff`` alone never
        lists it, which would make the mode a false green."""
        (git_repo / "serving" / "brand_new.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        p = self._run_cli_in(git_repo, "--changed-only", "--no-baseline",
                             "--json")
        assert p.returncode == 1, p.stdout + p.stderr
        d = json.loads(p.stdout)
        assert d["files_analyzed"] == 1
        assert all(f["path"].endswith("brand_new.py")
                   for f in d["findings"])

    def test_changed_only_respects_path_narrowing(self, git_repo):
        (git_repo / "serving" / "clean.py").write_text(
            "class E:\n    def f(self, req):\n"
            "        with self._lock:\n"
            "            req.future.result()\n")
        other = git_repo / "other"
        other.mkdir()
        p = self._run_cli_in(git_repo, str(other), "--changed-only",
                             "--no-baseline")
        assert p.returncode == 0   # the diff is outside the given path

    def test_changed_only_base_ref(self, git_repo):
        """--base-ref pins the diff base: vs HEAD~1 the seed commit's
        files count as changed."""
        (git_repo / "serving" / "extra.py").write_text("x = 1\n")
        self._git(git_repo, "add", "-A")
        self._git(git_repo, "-c", "commit.gpgsign=false", "commit",
                  "-q", "-m", "second")
        p = self._run_cli_in(git_repo, "--changed-only",
                             "--base-ref", "HEAD~1", "--no-baseline",
                             "--json")
        assert p.returncode == 0, p.stdout + p.stderr
        assert json.loads(p.stdout)["files_analyzed"] == 1

    def test_changed_only_usage_errors(self, git_repo, tmp_path):
        p = self._run_cli_in(git_repo, "--changed-only",
                             "--base-ref", "no-such-ref")
        assert p.returncode == 2
        p = self._run_cli_in(git_repo, "--changed-only",
                             "--write-baseline")
        assert p.returncode == 2   # partial-view baseline refused
        p = self._run_cli()        # no paths, no --changed-only
        assert p.returncode == 2

    def test_write_baseline_refuses_partial_view(self, tmp_path):
        """Review regression: a file that fails to parse must abort the
        baseline write — regenerating from a partial view would silently
        drop that file's waived findings."""
        bad = tmp_path / "serving"
        bad.mkdir()
        (bad / "e.py").write_text("def broken(:\n")
        bp = tmp_path / "bl.json"
        p = self._run_cli(str(bad), "--baseline", str(bp),
                          "--write-baseline")
        assert p.returncode == 1
        assert "NOT written" in p.stderr
        assert not bp.exists()
