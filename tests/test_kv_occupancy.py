"""KV occupancy -> 1.0 (ISSUE 13): on-demand block allocation, QoS-aware
preemption with recompute-on-resume, and the automatic prefix cache
(serving/paging.py + serving/generation.py + serving/cluster.py).

Acceptance criteria exercised here:
- ``allocate="reserve"`` (the default) stays bitwise-identical to the
  pre-on-demand engine, and ``allocate="on_demand"`` greedy/sampled
  streams equal their reserve-mode twins token for token;
- a preempted-then-resumed stream — evicted mid-generation to reclaim KV
  blocks, requeued through the prefill path with its generated-so-far
  tokens appended to the prompt — is bitwise-equal to its unpreempted
  run (per-request keys fold the token index, so sampling is
  position-stable), and the ONE-donated-executable bound
  ``len(buckets) + 1`` holds throughout;
- preemption respects QoS: victims are chosen lowest-class-first, a
  stream never evicts a higher class, ``TenantPolicy.preemptible=False``
  exempts a tenant, and an unresumable victim sheds typed 'preempted';
- the automatic prefix cache reuses retired streams' full blocks on a
  longest block-aligned token-prefix match with NO API opt-in, bounded
  by an LRU, bitwise-inert on stream content;
- preemption racing the watchdog/cache-rebuild path frees a victim's
  epoch-staled blocks exactly once (the PR 6 _clear_slot discipline).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models import TransformerConfig, init_params
from deeplearning4j_tpu.serving import (
    BlockAllocator, GenerationEngine, KVBlocksExhaustedError,
    PreemptedError, PrefixCache, QosPolicy, blocks_for_tokens,
)

CFG = TransformerConfig(vocab_size=50, hidden=32, layers=2, heads=2,
                        mlp_dim=64, max_seq=64, dtype=jnp.float32,
                        causal=True, attention_impl="full", remat=False)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, n).astype(np.int32)


# ---------------------------------------------------------------------------
# BlockAllocator.free_batch: a victim's whole footprint under one lock
# ---------------------------------------------------------------------------
class TestFreeBatch:
    def test_frees_multiple_lists_atomically(self):
        a = BlockAllocator(9)
        x, y = a.alloc(3), a.alloc(2)
        a.free_batch([x, y])
        assert a.free_count == a.capacity
        assert all(a.refcount(b) == 0 for b in x + y)

    def test_double_free_across_batch_is_rejected_untouched(self):
        a = BlockAllocator(9)
        x = a.alloc(2)
        with pytest.raises(ValueError, match="double free"):
            a.free_batch([x, x])          # refcount 1, two drops
        # validation ran BEFORE any mutation: nothing was freed
        assert all(a.refcount(b) == 1 for b in x)
        assert a.in_use == 2

    def test_shared_block_with_enough_refs_frees_per_holder(self):
        a = BlockAllocator(9)
        x = a.alloc(2)
        a.incref(x)                        # two holders
        a.free_batch([x, x])               # both drop in one batch
        assert a.free_count == a.capacity


# ---------------------------------------------------------------------------
# On-demand allocation: prompt-blocks-only seating, lazy growth
# ---------------------------------------------------------------------------
class TestOnDemandAllocation:
    def test_on_demand_greedy_equals_reserve(self, params):
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8) as eng:
            want = eng.generate(prompt(5), max_new_tokens=12,
                                eos_id=None, timeout=120)
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8,
                              allocate="on_demand") as eng:
            got = eng.generate(prompt(5), max_new_tokens=12,
                               eos_id=None, timeout=120)
            assert eng.compiled_signatures() <= len(eng.buckets) + 1
        assert got == want

    def test_seat_demand_is_prompt_blocks_only(self, params):
        # worst case: ceil((4+24)/8) = 4 blocks; pool capacity 4 — reserve
        # can hold ONE such stream, on_demand seats BOTH (1 block each)
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            assert eng._fresh_blocks_needed(0, 4, 24) == 4
            assert eng._fresh_blocks_needed(0, 4, 24, admit=True) == 1

    def test_structural_gate_keeps_worst_case(self, params):
        # a request whose WHOLE footprint exceeds the pool can never
        # complete under any allocator: still sheds typed at submit
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=3,
                              allocate="on_demand") as eng:
            with pytest.raises(KVBlocksExhaustedError) as ei:
                eng.submit(prompt(4), max_new_tokens=24)
            assert ei.value.reason == "kv_blocks_exhausted"

    def test_on_demand_rejects_contiguous_cache(self, params):
        with pytest.raises(ValueError, match="on_demand.*paged"):
            GenerationEngine(params, CFG, slots=2, max_len=32,
                             paged=False, allocate="on_demand")
        with pytest.raises(ValueError, match="allocate must be"):
            GenerationEngine(params, CFG, slots=2, max_len=32,
                             block_size=8, allocate="lazy")

    def test_reservation_slack_gauge_splits_from_fragmentation(
            self, params):
        """reserve holds worst-case tail blocks idle (slack > 0);
        on_demand keeps at most the next write target (slack 0 at
        seating). Sampled deterministically: a blocking on_token wedges
        the scheduler right after the post-prefill gauge update."""
        import threading

        for allocate, want_slack in (("reserve", 2), ("on_demand", 0)):
            seen = threading.Event()
            release = threading.Event()
            slack = []

            def hold(tok, _n=[0]):
                _n[0] += 1
                if _n[0] == 2:     # token 2: post-prefill gauges landed
                    seen.set()
                    release.wait(30)

            with GenerationEngine(params, CFG, slots=2, max_len=32,
                                  block_size=8,
                                  allocate=allocate) as eng:
                h = eng.submit(prompt(4), max_new_tokens=20, eos_id=None,
                               on_token=hold)
                assert seen.wait(60)
                slack.append(eng.metrics.kv_reservation_slack.value)
                release.set()
                h.result(timeout=120)
            # prompt 4 -> 1 touched block; reserve maps ceil(24/8)=3
            assert slack[-1] == want_slack, (allocate, slack)


# ---------------------------------------------------------------------------
# Preemption with recompute-on-resume
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_preempted_stream_resumes_bitwise_equal(self, params):
        """THE acceptance test: a tight pool forces eviction mid-stream;
        both streams complete, the victim's tokens equal its unpreempted
        (solo) run, and the signature bound holds."""
        solo = []
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8) as eng:
            for s in (0, 1):
                solo.append(eng.generate(prompt(4, s), max_new_tokens=20,
                                         eos_id=None, timeout=120))
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            hs = [eng.submit(prompt(4, s), max_new_tokens=20, eos_id=None)
                  for s in (0, 1)]
            got = [h.result(timeout=120) for h in hs]
            assert eng.metrics.preemptions_total.value >= 1
            assert eng.compiled_signatures() <= len(eng.buckets) + 1
            # TTFT exactly once per stream, preemptions notwithstanding
            # (review find: the resume gate must key on resume_step, not
            # the resumed flag, or victims could double- or zero-count)
            assert eng.metrics.ttft_ms.count == 2
        assert got == solo

    def test_sampled_preempted_stream_is_position_stable(self, params):
        solo = []
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8) as eng:
            for s in (3, 4):
                solo.append(eng.generate(
                    prompt(4, s), max_new_tokens=20, temperature=1.0,
                    top_k=8, seed=s, eos_id=None, timeout=120))
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            hs = [eng.submit(prompt(4, s), max_new_tokens=20,
                             temperature=1.0, top_k=8, seed=s,
                             eos_id=None) for s in (3, 4)]
            got = [h.result(timeout=120) for h in hs]
            assert eng.metrics.preemptions_total.value >= 1
        assert got == solo

    def test_resume_through_feed_path_when_prompt_outgrows_ladder(
            self, params):
        """A custom short bucket ladder: the recompute prompt (original
        prompt + generated tokens) exceeds the top prefill bucket, so
        the victim rebuilds through the decode-feed path — slower, but
        bitwise the same stream."""
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, buckets=(8,)) as eng:
            solo = [eng.generate(prompt(4, s), max_new_tokens=20,
                                 eos_id=None, timeout=120)
                    for s in (0, 1)]
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, buckets=(8,), num_blocks=5,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            hs = [eng.submit(prompt(4, s), max_new_tokens=20, eos_id=None)
                  for s in (0, 1)]
            got = [h.result(timeout=120) for h in hs]
            assert eng.metrics.preemptions_total.value >= 1
        assert got == solo

    def test_victims_by_tenant_class_batch_first(self, params):
        """QoS: the batch-class resident is evicted for the interactive
        stream's boundary crossing, never the other way around."""
        qos = QosPolicy(tenants={
            "fast": {"priority": "interactive"},
            "slow": {"priority": "batch"}})
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand", qos=qos,
                              queue_capacity=8) as eng:
            hb = eng.submit(prompt(4, 1), max_new_tokens=20, eos_id=None,
                            tenant="slow")
            ha = eng.submit(prompt(4, 0), max_new_tokens=20, eos_id=None,
                            tenant="fast")
            ra, rb = ha.result(timeout=120), hb.result(timeout=120)
            assert eng.metrics.preemptions_total.value >= 1
            # the interactive stream was never evicted
            assert ha._req.x.preemptions == 0
            assert hb._req.x.preemptions >= 1
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8) as eng:
            assert ra == eng.generate(prompt(4, 0), max_new_tokens=20,
                                      eos_id=None, timeout=120)
            assert rb == eng.generate(prompt(4, 1), max_new_tokens=20,
                                      eos_id=None, timeout=120)

    def test_non_preemptible_tenant_is_exempt(self, params):
        """preemptible=False shields a tenant from being chosen as
        someone ELSE's victim: any eviction it suffers is a
        self-preemption at its own boundary crossing (always legal —
        the pool cannot serve it any other way)."""
        from deeplearning4j_tpu.serving import Tracer

        qos = QosPolicy(tenants={
            "pinned": {"priority": "batch", "preemptible": False}})
        tracer = Tracer(enabled=True, sample_rate=1.0)
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand", qos=qos,
                              tracer=tracer, queue_capacity=8) as eng:
            hp = eng.submit(prompt(4, 1), max_new_tokens=20, eos_id=None,
                            tenant="pinned")
            ho = eng.submit(prompt(4, 0), max_new_tokens=20, eos_id=None)
            ho.result(timeout=120)
            hp.result(timeout=120)
            assert eng.metrics.preemptions_total.value >= 1
            evictions = [a for name, _t, a in hp._req.trace.events
                         if name == "preempt"]
            # every eviction the pinned tenant suffered was BY ITSELF
            assert all(a.get("self_preempted") for a in evictions)

    def test_batch_never_evicts_interactive(self, params):
        qos = QosPolicy(tenants={
            "fast": {"priority": "interactive"},
            "slow": {"priority": "batch"}})
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand", qos=qos,
                              queue_capacity=8) as eng:
            ha = eng.submit(prompt(4, 0), max_new_tokens=20, eos_id=None,
                            tenant="fast")
            hb = eng.submit(prompt(4, 1), max_new_tokens=20, eos_id=None,
                            tenant="slow")
            ha.result(timeout=120)
            hb.result(timeout=120)
            assert ha._req.x.preemptions == 0

    def test_unresumable_victim_sheds_typed_preempted(self, params):
        """Shared-prefix pins grow under a running stream; when its
        blocks are gone and its footprint can never fit again, the
        terminal is typed 'preempted' (tokens were already delivered —
        the caller resubmits the whole request)."""
        with GenerationEngine(params, CFG, slots=2, max_len=64,
                              block_size=8, num_blocks=9,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            # worst case: ceil((4+28)/8) = 4 of 8 usable blocks
            h = eng.submit(prompt(4), max_new_tokens=28, eos_id=None)
            while len(h.tokens_so_far()) < 2:
                time.sleep(0.001)
            # pin 5 blocks: usable drops to 3 < the stream's worst case
            eng.register_prefix(prompt(40, seed=9), timeout=60.0)
            with pytest.raises(PreemptedError) as ei:
                h.result(timeout=120)
            assert ei.value.reason == "preempted"
            assert ei.value.tokens_generated >= 1
            assert len(h.tokens_so_far()) >= 1
            assert eng.metrics.rejections_by_reason.get("preempted") == 1
            slo = eng.metrics.slo_snapshot()["60s"]["errors_by_reason"]
            assert slo.get("preempted") == 1


# ---------------------------------------------------------------------------
# Automatic prefix cache (no API opt-in)
# ---------------------------------------------------------------------------
class TestAutomaticPrefixCache:
    def test_shared_system_prompt_hits_without_opt_in(self, params):
        sysp = prompt(17, seed=7)          # 2 full blocks + partial tail
        p1 = np.concatenate([sysp, prompt(3, 1)]).astype(np.int32)
        p2 = np.concatenate([sysp, prompt(3, 2)]).astype(np.int32)
        with GenerationEngine(params, CFG, slots=2, max_len=48,
                              block_size=8) as eng:
            want = [eng.generate(p, max_new_tokens=5, timeout=120)
                    for p in (p1, p2)]
        with GenerationEngine(params, CFG, slots=2, max_len=48,
                              block_size=8,
                              prefix_cache_blocks=16) as eng:
            a = eng.generate(p1, max_new_tokens=5, timeout=120)
            b = eng.generate(p2, max_new_tokens=5, timeout=120)
            m = eng.metrics
            assert m.prefix_cache_hits_total.value == 1
            assert m.prefix_cache_inserts_total.value >= 1
            # the hit stream skipped its prefill entirely
            assert m.prefills_total.value == 1
        assert [a, b] == want

    def test_sampled_streams_bitwise_inert(self, params):
        sysp = prompt(16, seed=7)
        ps = [np.concatenate([sysp, prompt(4, s)]).astype(np.int32)
              for s in (1, 2, 3)]
        kw = dict(max_new_tokens=6, temperature=1.0, top_k=6, timeout=120)
        with GenerationEngine(params, CFG, slots=2, max_len=48,
                              block_size=8) as eng:
            want = [eng.generate(p, seed=s, **kw)
                    for s, p in enumerate(ps)]
        with GenerationEngine(params, CFG, slots=2, max_len=48,
                              block_size=8,
                              prefix_cache_blocks=16) as eng:
            got = [eng.generate(p, seed=s, **kw)
                   for s, p in enumerate(ps)]
            assert eng.metrics.prefix_cache_hits_total.value >= 2
        assert got == want

    def test_lru_bound_and_eviction(self, params):
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8,
                              prefix_cache_blocks=3) as eng:
            for s in range(6):             # distinct prompts, no reuse
                eng.generate(prompt(9, seed=s + 20), max_new_tokens=4,
                             timeout=120)
            assert eng._prefix_cache.total_blocks <= 3
            assert eng.metrics.prefix_cache_evictions_total.value >= 1
            assert eng.metrics.prefix_cache_blocks.value <= 3

    def test_cached_blocks_reclaimed_on_demand_not_gating(self, params):
        """A full cache never blocks admission: its entries evict the
        moment a seat demand needs the blocks (reclaimable capacity,
        which is also why kv_blocks_usable ignores it)."""
        with GenerationEngine(params, CFG, slots=1, max_len=32,
                              block_size=8, num_blocks=5,
                              prefix_cache_blocks=4,
                              queue_capacity=8) as eng:
            eng.generate(prompt(9, seed=1), max_new_tokens=4, timeout=120)
            assert eng._prefix_cache.total_blocks >= 1
            # worst case 4 blocks == whole pool: forces cache eviction
            assert eng.generate(prompt(4, seed=2), max_new_tokens=26,
                                eos_id=None, timeout=120)

    def test_drain_releases_cache_blocks(self, params):
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8,
                              prefix_cache_blocks=8) as eng:
            eng.generate(prompt(9, seed=1), max_new_tokens=4, timeout=120)
            assert eng._prefix_cache.total_blocks >= 1
            assert eng.drain(timeout=60.0)
            assert eng._allocator.free_count == eng._allocator.capacity

    def test_cache_survives_bookkeeping_on_rebuild(self, params):
        """A cache rebuild voids every entry WITHOUT freeing into the
        fresh allocator (the stale references belong to the dead pool)."""
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8,
                              prefix_cache_blocks=8) as eng:
            eng.generate(prompt(9, seed=1), max_new_tokens=4, timeout=120)
            assert eng._prefix_cache.total_blocks >= 1
            eng._reset_cache()
            assert eng._prefix_cache.total_blocks == 0
            assert eng._allocator.free_count == eng._allocator.capacity
            # and the engine still serves
            assert eng.generate(prompt(5), max_new_tokens=4, timeout=120)

    def test_prefix_cache_requires_paged(self, params):
        with pytest.raises(ValueError, match="prefix_cache_blocks"):
            GenerationEngine(params, CFG, slots=2, max_len=32,
                             paged=False, prefix_cache_blocks=8)

    def test_unit_match_is_block_granular_and_lru(self):
        a = BlockAllocator(17)
        c = PrefixCache(a, 4, capacity_blocks=8)
        t1 = np.arange(8, dtype=np.int32)
        b1 = a.alloc(2)
        assert c.insert(t1, b1)
        # full match capped at (len-1)//B blocks: identical prompt still
        # leaves one token to feed
        hit = c.match(np.arange(8, dtype=np.int32))
        assert hit is not None and hit[1] == 1
        hit = c.match(np.arange(12, dtype=np.int32))
        assert hit is not None and hit[1] == 2
        assert c.match(np.arange(3, dtype=np.int32)) is None   # < 1 block
        miss = np.concatenate([[9, 9, 9, 9],
                               np.arange(4)]).astype(np.int32)
        assert c.match(miss) is None      # prefix, not substring
        # duplicate coverage rejected, and the offered refs come back
        b2 = a.alloc(1)
        free_before = a.free_count
        assert not c.insert(t1[:4], b2)   # an entry already covers these
        assert a.free_count == free_before + 1

    def test_cancelled_cache_hit_request_frees_match_refs(self, params):
        """Review find: a queued request cancelled before seating whose
        prompt matched the cache must free the planner's match refs —
        a leak would keep evicted cache blocks off the free list
        forever, silently shrinking the pool."""
        import threading

        sysp = prompt(17, seed=7)
        p1 = np.concatenate([sysp, prompt(3, 1)]).astype(np.int32)
        p2 = np.concatenate([sysp, prompt(3, 2)]).astype(np.int32)
        seen, release = threading.Event(), threading.Event()

        def hold(tok):
            seen.set()
            release.wait(30)

        with GenerationEngine(params, CFG, slots=1, max_len=48,
                              block_size=8, prefix_cache_blocks=16,
                              queue_capacity=8) as eng:
            eng.generate(p1, max_new_tokens=4, timeout=120)  # seeds cache
            blocker = eng.submit(prompt(5, 9), max_new_tokens=12,
                                 eos_id=None, on_token=hold)
            assert seen.wait(60)          # slot wedged: queue backs up
            victim = eng.submit(p2, max_new_tokens=4)
            assert victim.future.cancel()  # cancelled while queued
            release.set()
            blocker.result(timeout=120)
            # a clean follow-up stream drains everything; afterwards the
            # only refs left are the cache's own — dropping them must
            # return the WHOLE pool (a leaked match ref would not)
            eng.generate(prompt(6, 11), max_new_tokens=4, timeout=120)
            deadline = time.time() + 30
            while eng.live_slots:
                assert time.time() < deadline
                time.sleep(0.01)
            eng._prefix_cache.release_all()
            assert eng._allocator.free_count == eng._allocator.capacity

    def test_match_and_ref_survives_concurrent_release(self):
        """Review find: the match→seat handoff must own its refs — a
        release_all (warmup finishing, drain) between match and seating
        could otherwise free the matched blocks and hand them back to
        the very stream as 'fresh', corrupting its own shared prefix."""
        a = BlockAllocator(17)
        c = PrefixCache(a, 4, capacity_blocks=8)
        toks = np.arange(8, dtype=np.int32)
        blocks = a.alloc(2)
        assert c.insert(toks, blocks)
        hit = c.match_and_ref(np.arange(12, dtype=np.int32))
        assert hit is not None
        _e, m, owned = hit
        assert m == 2 and all(a.refcount(b) == 2 for b in owned)
        c.release_all()                    # the cache's refs drop...
        assert all(a.refcount(b) == 1 for b in owned)   # ...ours hold
        # the blocks are NOT on the free list while the seat holds them
        grabbed = a.alloc(a.free_count)
        assert not set(owned) & set(grabbed)
        a.free(grabbed)
        a.free(owned)                      # seat path releases at retire
        assert a.free_count == a.capacity


# ---------------------------------------------------------------------------
# Chaos: preemption racing the watchdog / cache-rebuild path
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestPreemptionWatchdogRace:
    def test_stale_epoch_preemption_frees_nothing(self, params):
        """The epoch guard: a zombie scheduler's preemption attempt
        against a bumped epoch must not touch the table or free a single
        block (they belong to the replacement's pool now)."""
        import threading

        seen, release = threading.Event(), threading.Event()

        def hold(tok, _n=[0]):
            _n[0] += 1
            if _n[0] == 2:
                seen.set()
                release.wait(30)

        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, allocate="on_demand") as eng:
            h = eng.submit(prompt(4), max_new_tokens=10, eos_id=None,
                           on_token=hold)
            assert seen.wait(60)
            st = eng._slots[0]
            assert st is not None
            held = list(st.blocks)
            free_before = eng._allocator.free_count
            out = eng._preempt_for(0, st, eng._epoch + 1)   # stale epoch
            assert out == "stale"
            assert st.blocks == held
            assert eng._allocator.free_count == free_before
            release.set()
            h.result(timeout=120)

    def test_watchdog_restart_mid_preemption_workload_no_double_free(
            self, params):
        """Seeded chaos: a decode hang trips the watchdog while an
        on-demand engine is actively preempting on a starved pool. The
        victims' epoch-staled blocks are freed exactly once — a double
        free into the FRESH allocator would raise inside the scheduler
        and poison every later stream — and the rebuilt engine's
        accounting drains back to a full free list."""
        from deeplearning4j_tpu.serving import FaultPlan

        plan = FaultPlan(seed=0).delay("generation.decode_step", ms=900,
                                       at=(6,))
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand",
                              queue_capacity=8) as eng:
            eng.generate(prompt(5), max_new_tokens=2, timeout=120)
            eng.arm_watchdog(200)
            with plan:
                hs = [eng.submit(prompt(4, s), max_new_tokens=20,
                                 eos_id=None) for s in (0, 1)]
                for h in hs:
                    with pytest.raises(Exception):
                        h.result(timeout=60)
            time.sleep(1.0)    # zombie wakes against its abandoned cache
            # fresh pool serves clean bitwise streams, zero leaked blocks
            got = [eng.generate(prompt(4, s), max_new_tokens=20,
                                eos_id=None, timeout=120) for s in (0, 1)]
            deadline = time.time() + 30
            while eng._allocator.free_count != eng._allocator.capacity:
                assert time.time() < deadline, "leaked blocks"
                time.sleep(0.01)
            assert eng.compiled_signatures() <= len(eng.buckets) + 1
        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8) as eng:
            solo = [eng.generate(prompt(4, s), max_new_tokens=20,
                                 eos_id=None, timeout=120)
                    for s in (0, 1)]
        assert got == solo


# ---------------------------------------------------------------------------
# Cluster integration: heartbeat fields, routing, elasticity signal
# ---------------------------------------------------------------------------
class TestClusterIntegration:
    def test_host_status_wire_carries_allocate_and_preemptions(self):
        import json

        from deeplearning4j_tpu.serving import HostStatus

        st = HostStatus(host_id=3, allocate="on_demand",
                        preemptions_total=7, seq=1)
        back = HostStatus.from_dict(json.loads(json.dumps(st.to_dict())))
        assert back.allocate == "on_demand"
        assert back.preemptions_total == 7
        # pre-upgrade payloads default to the conservative read
        old = st.to_dict()
        del old["allocate"], old["preemptions_total"]
        back = HostStatus.from_dict(old)
        assert back.allocate == "reserve"
        assert back.preemptions_total == 0

    def test_headroom_gates_on_demand_hosts_on_admit_demand(self):
        from deeplearning4j_tpu.serving import HostStatus
        from deeplearning4j_tpu.serving.cluster import ClusterFrontDoor

        st = HostStatus(host_id=0, has_generate=True, slots=4,
                        free_slots=1, kv_blocks_total=20,
                        kv_blocks_usable=16, kv_blocks_free=3,
                        gen_queue_depth=10, gen_queue_capacity=10)
        hr = ClusterFrontDoor._headroom
        # worst case 8 > 3 free: a reserve host cannot seat immediately
        # and its queue is full -> no headroom
        assert not hr(None, st, "generate", 1, 8, 2)
        # the same host on_demand seats on the 2-block admit demand
        st.allocate = "on_demand"
        assert hr(None, st, "generate", 1, 8, 2)
        # the structural bound still applies to every mode
        assert not hr(None, st, "generate", 1, 17, 2)

    def test_loopback_status_reports_allocate_mode(self, params):
        from deeplearning4j_tpu.serving import LoopbackHost

        with GenerationEngine(params, CFG, slots=2, max_len=32,
                              block_size=8,
                              allocate="on_demand") as eng:
            st = LoopbackHost(0, generation=eng).status()
            assert st.allocate == "on_demand"
            assert st.preemptions_total == 0

    def test_planner_preemption_rate_is_a_join_signal(self):
        from deeplearning4j_tpu.serving import (
            ElasticityPlanner, ElasticityPolicy)

        def snap(preempt):
            return {"fleet": {"alive": 3, "draining": 0, "slots": 12,
                              "free_slots": 4,
                              "preemptions_total": preempt},
                    "front_doors": [], "hosts": {}}

        p = ElasticityPlanner(ElasticityPolicy(trend_windows=2))
        assert p.observe(snap(0))["action"] == "hold"   # first never acts
        d = p.observe(snap(3))
        assert d["action"] == "hold" and d["preemptions_delta"] == 3
        d = p.observe(snap(6))
        assert d["action"] == "join"
        assert "preemption" in d["reason"]
        # steady counter (no new preemptions): pressure streak resets
        p2 = ElasticityPlanner(ElasticityPolicy(trend_windows=2))
        for i, s in enumerate((0, 0, 0, 0)):
            d = p2.observe(snap(s))
        assert d["action"] == "hold" and d["preemptions_delta"] == 0


# ---------------------------------------------------------------------------
# Observability: the new metrics ride /api/serving
# ---------------------------------------------------------------------------
class TestMetricsSurface:
    def test_snapshot_carries_occupancy_metrics(self):
        from deeplearning4j_tpu.serving import ServingMetrics

        snap = ServingMetrics().snapshot()
        for key in ("kv_reservation_slack", "prefix_cache_blocks",
                    "preemptions_total", "prefix_cache_hits_total",
                    "prefix_cache_inserts_total",
                    "prefix_cache_evictions_total"):
            assert key in snap, key

    def test_preempted_reason_registered_exactly_once(self):
        from deeplearning4j_tpu.serving.tracing import TERMINAL_REASONS

        assert TERMINAL_REASONS.count("preempted") == 1
