"""De-dispatched fit(): fuseSteps training steps per XLA executable
(lax.scan over stacked minibatches — the per-STEP analog of SURVEY §3.1's
per-op JNI-dispatch deletion). Parity contract: the fused path must produce
exactly the same parameters as the per-step path for deterministic nets."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization, DenseLayer,
                                               OutputLayer)
from deeplearning4j_tpu.train.updaters import Adam

RNG = np.random.default_rng(11)


def _mlp_conf(seed=0, bn=False):
    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(nOut=16, activation="TANH")))
    if bn:
        b = b.layer(BatchNormalization())
    return (b.layer(OutputLayer(nOut=3, lossFunction="MCXENT"))
            .setInputType(InputType.feedForward(6)).build())


def _batches(n, B=8):
    out = []
    for _ in range(n):
        x = RNG.normal(size=(B, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, B)]
        out.append(DataSet(x, y))
    return out


def _params_flat(net):
    return np.asarray(net.params().toNumpy())


class TestFusedFitMLN:
    def test_parity_with_per_step_path(self):
        batches = _batches(16)
        fused = MultiLayerNetwork(_mlp_conf()).init()
        single = MultiLayerNetwork(_mlp_conf()).init()
        single.fuseSteps = 0  # force the per-step executable
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        assert fused._iteration == single._iteration == 16
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_parity_with_batchnorm_state(self):
        batches = _batches(16)
        fused = MultiLayerNetwork(_mlp_conf(bn=True)).init()
        single = MultiLayerNetwork(_mlp_conf(bn=True)).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)
        # running stats threaded through the scan carry
        np.testing.assert_allclose(np.asarray(fused._state[1]["mean"]),
                                   np.asarray(single._state[1]["mean"]),
                                   atol=1e-6)

    def test_leftover_steps_run(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(ListDataSetIterator(_batches(11)))  # 8 fused + 3 single
        assert net._iteration == 11
        assert np.isfinite(net.score())

    def test_epoch_boundaries_fuse(self):
        # 3 batches x 4 epochs = 12 steps -> one 8-chunk + 4 leftovers
        batches = _batches(3)
        fused = MultiLayerNetwork(_mlp_conf()).init()
        single = MultiLayerNetwork(_mlp_conf()).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches), epochs=4)
        single.fit(ListDataSetIterator(batches), epochs=4)
        assert fused._iteration == single._iteration == 12
        assert fused._epoch == single._epoch == 4
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_shape_change_drains_buffer(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        mixed = _batches(3, B=8) + _batches(3, B=4) + _batches(2, B=8)
        net.fit(ListDataSetIterator(mixed))
        assert net._iteration == 8
        assert np.isfinite(net.score())

    def test_listeners_force_per_step(self):
        calls = []

        class L:
            def iterationDone(self, net, it, ep):
                calls.append(it)

        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setListeners(L())
        net.fit(ListDataSetIterator(_batches(10)))
        assert calls == list(range(1, 11))

    def test_training_converges_through_fused_path(self):
        x = RNG.normal(size=(64, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[x[:, :3].argmax(1)]
        batches = [DataSet(x[i:i + 8], y[i:i + 8]) for i in range(0, 64, 8)]
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(ListDataSetIterator(batches), epochs=30)
        out = np.asarray(net.output(x).toNumpy())
        assert (out.argmax(1) == y.argmax(1)).mean() > 0.8


class TestFusedFitCG:
    def _cg_conf(self, seed=0):
        return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
                .graphBuilder()
                .addInputs("in")
                .addLayer("h", DenseLayer(nOut=16, activation="TANH"), "in")
                .addLayer("out", OutputLayer(nOut=3, lossFunction="MCXENT"), "h")
                .setOutputs("out")
                .setInputTypes(InputType.feedForward(6)).build())

    def test_parity_with_per_step_path(self):
        batches = _batches(16)
        fused = ComputationGraph(self._cg_conf()).init()
        single = ComputationGraph(self._cg_conf()).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        assert fused._iteration == single._iteration == 16
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_leftover_and_score(self):
        net = ComputationGraph(self._cg_conf()).init()
        net.fit(ListDataSetIterator(_batches(9)))
        assert net._iteration == 9
        assert np.isfinite(net.score())
