"""De-dispatched fit(): fuseSteps training steps per XLA executable
(lax.scan over stacked minibatches — the per-STEP analog of SURVEY §3.1's
per-op JNI-dispatch deletion). Parity contract: the fused path must produce
exactly the same parameters as the per-step path for deterministic nets."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization, DenseLayer,
                                               OutputLayer)
from deeplearning4j_tpu.train.updaters import Adam

RNG = np.random.default_rng(11)


def _mlp_conf(seed=0, bn=False):
    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(nOut=16, activation="TANH")))
    if bn:
        b = b.layer(BatchNormalization())
    return (b.layer(OutputLayer(nOut=3, lossFunction="MCXENT"))
            .setInputType(InputType.feedForward(6)).build())


def _batches(n, B=8):
    out = []
    for _ in range(n):
        x = RNG.normal(size=(B, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, B)]
        out.append(DataSet(x, y))
    return out


def _params_flat(net):
    return np.asarray(net.params().toNumpy())


class TestFusedFitMLN:
    def test_parity_with_per_step_path(self):
        batches = _batches(16)
        fused = MultiLayerNetwork(_mlp_conf()).init()
        single = MultiLayerNetwork(_mlp_conf()).init()
        single.fuseSteps = 0  # force the per-step executable
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        assert fused._iteration == single._iteration == 16
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_parity_with_batchnorm_state(self):
        batches = _batches(16)
        fused = MultiLayerNetwork(_mlp_conf(bn=True)).init()
        single = MultiLayerNetwork(_mlp_conf(bn=True)).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)
        # running stats threaded through the scan carry
        np.testing.assert_allclose(np.asarray(fused._state[1]["mean"]),
                                   np.asarray(single._state[1]["mean"]),
                                   atol=1e-6)

    def test_leftover_steps_run(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(ListDataSetIterator(_batches(11)))  # 8 fused + 3 single
        assert net._iteration == 11
        assert np.isfinite(net.score())

    def test_epoch_boundaries_fuse(self):
        # 3 batches x 4 epochs = 12 steps -> one 8-chunk + 4 leftovers
        batches = _batches(3)
        fused = MultiLayerNetwork(_mlp_conf()).init()
        single = MultiLayerNetwork(_mlp_conf()).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches), epochs=4)
        single.fit(ListDataSetIterator(batches), epochs=4)
        assert fused._iteration == single._iteration == 12
        assert fused._epoch == single._epoch == 4
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_shape_change_drains_buffer(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        mixed = _batches(3, B=8) + _batches(3, B=4) + _batches(2, B=8)
        net.fit(ListDataSetIterator(mixed))
        assert net._iteration == 8
        assert np.isfinite(net.score())

    def test_unknown_listeners_force_per_step(self):
        """A listener without requiresModelAtIteration metadata (or with the
        conservative default) keeps the exact per-step path."""
        calls = []

        class L:
            def iterationDone(self, net, it, ep):
                calls.append(it)

        net = MultiLayerNetwork(_mlp_conf()).init()
        net.setListeners(L())
        net.fit(ListDataSetIterator(_batches(10)))
        assert calls == list(range(1, 11))

    def test_score_listener_fuses_with_identical_callbacks(self):
        """Round-3 verdict #3: a score-only listener must NOT disable the
        fused path, and the callback sequence (iteration, epoch, score) must
        be identical to the per-step path — parameters too."""
        from deeplearning4j_tpu.optimize.listeners import CollectScoresListener

        batches = _batches(16)
        runs = {}
        for name, fuse in (("fused", 8), ("single", 0)):
            net = MultiLayerNetwork(_mlp_conf()).init()
            net.fuseSteps = fuse
            seq = []

            class Rec(CollectScoresListener):
                def iterationDone(self, model, it, ep):
                    seq.append((it, ep, float(model.score())))
                    super().iterationDone(model, it, ep)

            net.setListeners(Rec(frequency=1))
            net.fit(ListDataSetIterator(batches), epochs=2)
            runs[name] = (_params_flat(net), seq, net._iteration)

        assert runs["fused"][2] == runs["single"][2] == 32
        f_seq, s_seq = runs["fused"][1], runs["single"][1]
        assert [(i, e) for i, e, _ in f_seq] == [(i, e) for i, e, _ in s_seq]
        np.testing.assert_allclose([s for _, _, s in f_seq],
                                   [s for _, _, s in s_seq], atol=1e-6)
        np.testing.assert_allclose(runs["fused"][0], runs["single"][0],
                                   atol=1e-6)

    def test_model_boundary_listener_sees_current_params(self):
        """A listener that needs the live model at iteration k must observe
        exactly the params the per-step path would show at k — the scan is
        flushed at that boundary."""
        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        batches = _batches(12)
        snaps = {}

        class SnapAt(TrainingListener):
            def __init__(self, at):
                self.at = at

            def requiresModelAtIteration(self, it):
                return it in self.at

            def iterationDone(self, model, it, ep):
                if it in self.at:
                    snaps.setdefault(self._tag, {})[it] = _params_flat(model)

        for tag, fuse in (("fused", 8), ("single", 0)):
            net = MultiLayerNetwork(_mlp_conf()).init()
            net.fuseSteps = fuse
            lst = SnapAt({5, 11})
            lst._tag = tag
            net.setListeners(lst)
            net.fit(ListDataSetIterator(batches))
        for it in (5, 11):
            np.testing.assert_allclose(snaps["fused"][it],
                                       snaps["single"][it], atol=1e-6)

    def test_exception_mid_fit_preserves_completed_callbacks(self):
        """MLN mirror of the SameDiff test: an exception injected into the
        THIRD fused chunk's dispatch must still deliver the two completed
        (lag-buffered) chunks' callbacks via the except-path drain."""
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fuseSteps = 4
        calls = []

        class Rec:
            def requiresModelAtIteration(self, it):
                return False

            def iterationDone(self, model, it, ep):
                calls.append((it, float(model.score())))

        net.setListeners(Rec())
        orig = net._get_jitted("multi")
        n = {"calls": 0}

        def bomb(*args):
            n["calls"] += 1
            if n["calls"] == 3:
                raise RuntimeError("injected chunk failure")
            return orig(*args)

        net._jit_cache["multi"] = bomb
        from deeplearning4j_tpu.util import crash_reporting
        crash_reporting.crashDumpsEnabled(False)  # no dump file for the
        try:                                      # intentional failure
            with pytest.raises(RuntimeError, match="injected chunk failure"):
                net.fit(ListDataSetIterator(_batches(12)))
        finally:
            crash_reporting.crashDumpsEnabled(True)
        assert [i for i, _ in calls] == list(range(1, 9))
        assert all(np.isfinite(s) for _, s in calls)

    def test_replay_lag_zero_streams_per_chunk(self):
        """listenerReplayLag=0 (live streaming): callbacks fire right after
        each chunk, still in exact order/score parity with per-step."""
        from deeplearning4j_tpu.optimize.listeners import CollectScoresListener

        batches = _batches(10)
        runs = {}
        for name, (fuse, lag) in (("lag0", (4, 0)), ("single", (0, 0))):
            net = MultiLayerNetwork(_mlp_conf()).init()
            net.fuseSteps = fuse
            net.listenerReplayLag = lag
            seq = []

            class Rec(CollectScoresListener):
                def iterationDone(self, model, it, ep):
                    seq.append((it, float(model.score())))

            net.setListeners(Rec(frequency=1))
            net.fit(ListDataSetIterator(batches))
            runs[name] = seq
        assert [i for i, _ in runs["lag0"]] == [i for i, _ in runs["single"]]
        np.testing.assert_allclose([s for _, s in runs["lag0"]],
                                   [s for _, s in runs["single"]], atol=1e-6)

    def test_masked_batch_applies_after_buffered_steps(self):
        """Round-3 advisor: a masked DataSet arriving while unmasked steps
        sit in the fusion buffer must apply AFTER them (sequential order).
        Parity with the per-step path proves the ordering."""
        batches = _batches(5)
        # give batch 5 a labels mask (all ones: numerically neutral shape-
        # wise but routes through the masked/ineligible branch)
        masked = DataSet(batches[4].features, batches[4].labels,
                         labels_mask=np.ones((8,), np.float32))
        seq = batches[:4] + [masked] + _batches(3)
        fused = MultiLayerNetwork(_mlp_conf()).init()
        single = MultiLayerNetwork(_mlp_conf()).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(seq))
        single.fit(ListDataSetIterator(seq))
        assert fused._iteration == single._iteration == 8
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_device_cache_observes_inplace_mutation(self):
        """Round-3 advisor (medium): a pipeline that refills one
        preallocated buffer between fit calls must train on the fresh data,
        not a stale first-seen device copy."""
        x = RNG.normal(size=(8, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.integers(0, 3, 8)]
        reused = DataSet(x, y)
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(reused)
        p_before = _params_flat(net)
        # mutate the SAME buffers in place; a stale cache would replay the
        # old batch and produce the old update trajectory
        fresh = MultiLayerNetwork(_mlp_conf()).init()
        x2 = RNG.normal(size=(8, 6)).astype(np.float32)
        x[...] = x2
        net2_ds = DataSet(np.array(x2), np.array(y))
        fresh.fit(net2_ds)
        net_reinit = MultiLayerNetwork(_mlp_conf()).init()
        net_reinit._dev_cache = net._dev_cache  # share the warm cache
        net_reinit.fit(reused)  # same ids, mutated content
        np.testing.assert_allclose(_params_flat(net_reinit),
                                   _params_flat(fresh), atol=1e-6)

    def test_device_cache_byte_cap_and_streaming(self):
        from deeplearning4j_tpu.nn.multilayer import _DeviceCache

        cache = _DeviceCache(max_bytes=10 * 4)  # 10 floats
        a = np.ones(4, np.float32)
        b = np.ones(4, np.float32)
        c = np.ones(4, np.float32)
        cache.get_or_put([a], lambda: "A")
        cache.get_or_put([b], lambda: "B")
        assert cache._bytes <= 10 * 4
        cache.get_or_put([c], lambda: "C")  # evicts FIFO to fit
        assert cache._bytes <= 10 * 4
        # streaming: after _STREAM_MISSES consecutive misses, stop inserting
        small = _DeviceCache(max_bytes=1 << 20)
        for i in range(small._STREAM_MISSES + 5):
            small.get_or_put([np.full(2, i, np.float32)], lambda: i)
        assert len(small._d) <= small._STREAM_MISSES
        # disabled cache never stores
        off = _DeviceCache()
        off.enabled = False
        off.get_or_put([a], lambda: "X")
        assert not off._d

    def test_training_converges_through_fused_path(self):
        x = RNG.normal(size=(64, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[x[:, :3].argmax(1)]
        batches = [DataSet(x[i:i + 8], y[i:i + 8]) for i in range(0, 64, 8)]
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(ListDataSetIterator(batches), epochs=30)
        out = np.asarray(net.output(x).toNumpy())
        assert (out.argmax(1) == y.argmax(1)).mean() > 0.8


class TestFusedFitCG:
    def _cg_conf(self, seed=0):
        return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
                .graphBuilder()
                .addInputs("in")
                .addLayer("h", DenseLayer(nOut=16, activation="TANH"), "in")
                .addLayer("out", OutputLayer(nOut=3, lossFunction="MCXENT"), "h")
                .setOutputs("out")
                .setInputTypes(InputType.feedForward(6)).build())

    def test_parity_with_per_step_path(self):
        batches = _batches(16)
        fused = ComputationGraph(self._cg_conf()).init()
        single = ComputationGraph(self._cg_conf()).init()
        single.fuseSteps = 0
        fused.fit(ListDataSetIterator(batches))
        single.fit(ListDataSetIterator(batches))
        assert fused._iteration == single._iteration == 16
        np.testing.assert_allclose(_params_flat(fused), _params_flat(single),
                                   atol=1e-6)

    def test_leftover_and_score(self):
        net = ComputationGraph(self._cg_conf()).init()
        net.fit(ListDataSetIterator(_batches(9)))
        assert net._iteration == 9
        assert np.isfinite(net.score())

    def test_score_listener_fuses_with_identical_callbacks(self):
        """CG mirror of the MLN test: score-only listeners fuse, callback
        sequence and params identical to the per-step path."""
        from deeplearning4j_tpu.optimize.listeners import CollectScoresListener

        batches = _batches(12)
        runs = {}
        for name, fuse in (("fused", 8), ("single", 0)):
            net = ComputationGraph(self._cg_conf()).init()
            net.fuseSteps = fuse
            seq = []

            class Rec(CollectScoresListener):
                def iterationDone(self, model, it, ep):
                    seq.append((it, ep, float(model.score())))

            net.setListeners(Rec(frequency=1))
            net.fit(ListDataSetIterator(batches))
            runs[name] = (_params_flat(net), seq, net._iteration)

        assert runs["fused"][2] == runs["single"][2] == 12
        assert [(i, e) for i, e, _ in runs["fused"][1]] == \
            [(i, e) for i, e, _ in runs["single"][1]]
        np.testing.assert_allclose([s for _, _, s in runs["fused"][1]],
                                   [s for _, _, s in runs["single"][1]],
                                   atol=1e-6)
        np.testing.assert_allclose(runs["fused"][0], runs["single"][0],
                                   atol=1e-6)
