"""Systematic gradient-check sweep (ref: org.deeplearning4j.gradientcheck.* —
GradientCheckTests / CNNGradientCheckTest / LSTMGradientCheckTests /
VertexGradientCheckTests: 'THE correctness backbone', SURVEY.md §4.1).

Every case: tiny net, fp64, central differences vs jax.grad on a random
parameter subset. Layers with stochastic forward (dropout) are excluded, as
the reference excludes them."""
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    Convolution1DLayer, DenseLayer, Deconvolution2D, DepthwiseConvolution2D,
    ElementWiseMultiplicationLayer, EmbeddingSequenceLayer, GlobalPoolingLayer,
    GravesLSTM, LSTM, LastTimeStep, LocallyConnected1D, LocallyConnected2D,
    LossLayer, OutputLayer, PReLULayer, RnnOutputLayer, SeparableConvolution2D,
    SimpleRnn, SpaceToDepthLayer, SubsamplingLayer, Upsampling2D,
)
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.utils.gradientcheck import check_gradients, check_gradients_graph

RNG = np.random.default_rng(42)


def _mln(input_type, *layers):
    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1)).list())
    for l in layers:
        conf = conf.layer(l)
    return MultiLayerNetwork(conf.setInputType(input_type).build()).init()


def _ff_data(n, nin, ncls):
    x = RNG.normal(size=(n, nin)).astype(np.float64)
    y = np.eye(ncls)[RNG.integers(0, ncls, n)].astype(np.float64)
    return x, y


def _seq_data(n, t, nin, ncls):
    x = RNG.normal(size=(n, t, nin)).astype(np.float64)
    y = np.eye(ncls)[RNG.integers(0, ncls, (n, t))].astype(np.float64)
    return x, y


class TestDenseFamilies:
    @pytest.mark.parametrize("act", ["TANH", "SIGMOID", "SOFTPLUS", "ELU", "CUBE"])
    def test_dense_activations(self, act):
        net = _mln(InputType.feedForward(4),
                   DenseLayer(nOut=5, activation=act),
                   OutputLayer(nOut=3, lossFunction="MCXENT"))
        x, y = _ff_data(6, 4, 3)
        assert check_gradients(net, x, y, subset=40)

    @pytest.mark.parametrize("loss", ["MSE", "L1", "XENT", "HINGE", "KL_DIVERGENCE"])
    def test_loss_functions(self, loss):
        act = {"XENT": "SIGMOID", "KL_DIVERGENCE": "SOFTMAX"}.get(loss, "TANH")
        net = _mln(InputType.feedForward(4),
                   DenseLayer(nOut=5, activation="TANH"),
                   OutputLayer(nOut=3, activation=act, lossFunction=loss))
        x = RNG.normal(size=(6, 4))
        if loss in ("XENT",):
            y = RNG.integers(0, 2, (6, 3)).astype(np.float64)
        elif loss == "KL_DIVERGENCE":
            y = np.abs(RNG.normal(size=(6, 3))) + 0.1
            y = y / y.sum(-1, keepdims=True)
        elif loss == "HINGE":
            y = RNG.choice([-1.0, 1.0], (6, 3))
        else:
            y = RNG.normal(size=(6, 3))
        assert check_gradients(net, x, y, subset=40)

    def test_prelu_and_elementwise_mult(self):
        net = _mln(InputType.feedForward(4),
                   DenseLayer(nOut=6, activation="TANH"),
                   PReLULayer(inputShape=(6,)),
                   ElementWiseMultiplicationLayer(nIn=6),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x, y = _ff_data(5, 4, 2)
        assert check_gradients(net, x, y, subset=40)

    def test_batchnorm_dense(self):
        net = _mln(InputType.feedForward(4),
                   DenseLayer(nOut=6, activation="IDENTITY"),
                   BatchNormalization(activation="TANH"),
                   OutputLayer(nOut=3, lossFunction="MCXENT"))
        x, y = _ff_data(8, 4, 3)
        assert check_gradients(net, x, y, subset=40)


class TestConvFamilies:
    def test_conv2d_pool(self):
        net = _mln(InputType.convolutional(8, 8, 2),
                   ConvolutionLayer(nOut=3, kernelSize=(3, 3), activation="TANH"),
                   SubsamplingLayer(poolingType="AVG", kernelSize=(2, 2), stride=(2, 2)),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x = RNG.normal(size=(3, 2, 8, 8))
        y = np.eye(2)[RNG.integers(0, 2, 3)]
        assert check_gradients(net, x, y, subset=50)

    @pytest.mark.parametrize("layer", [
        SeparableConvolution2D(nOut=3, kernelSize=(3, 3), activation="TANH"),
        DepthwiseConvolution2D(kernelSize=(3, 3), depthMultiplier=2,
                               activation="TANH"),
        Deconvolution2D(nOut=3, kernelSize=(2, 2), stride=(2, 2),
                        activation="TANH"),
        LocallyConnected2D(nOut=3, kernelSize=(3, 3), activation="TANH"),
    ])
    def test_conv_variants(self, layer):
        net = _mln(InputType.convolutional(6, 6, 2),
                   layer,
                   GlobalPoolingLayer(poolingType="AVG"),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x = RNG.normal(size=(3, 2, 6, 6))
        y = np.eye(2)[RNG.integers(0, 2, 3)]
        assert check_gradients(net, x, y, subset=50)

    def test_conv1d(self):
        net = _mln(InputType.recurrent(3, 8),
                   Convolution1DLayer(nOut=4, kernelSize=3, activation="TANH"),
                   GlobalPoolingLayer(poolingType="MAX"),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x, _ = _seq_data(3, 8, 3, 2)
        y = np.eye(2)[RNG.integers(0, 2, 3)]
        assert check_gradients(net, x, y, subset=40)

    def test_space_to_depth_and_upsampling(self):
        net = _mln(InputType.convolutional(4, 4, 2),
                   Upsampling2D(size=(2, 2)),
                   SpaceToDepthLayer(blockSize=2),
                   ConvolutionLayer(nOut=2, kernelSize=(1, 1), activation="TANH"),
                   GlobalPoolingLayer(poolingType="AVG"),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x = RNG.normal(size=(2, 2, 4, 4))
        y = np.eye(2)[RNG.integers(0, 2, 2)]
        assert check_gradients(net, x, y, subset=40)


class TestRecurrentFamilies:
    @pytest.mark.parametrize("cell", [
        lambda: SimpleRnn(nOut=4, activation="TANH"),
        lambda: LSTM(nOut=4),
        lambda: GravesLSTM(nOut=4),
    ])
    def test_rnn_cells(self, cell):
        net = _mln(InputType.recurrent(3, 5),
                   cell(),
                   RnnOutputLayer(nOut=2, lossFunction="MCXENT"))
        x, y = _seq_data(3, 5, 3, 2)
        assert check_gradients(net, x, y, subset=50)

    def test_bidirectional_lasttimestep(self):
        net = _mln(InputType.recurrent(3, 5),
                   Bidirectional(fwd=LSTM(nOut=4)),
                   LastTimeStep(underlying=None),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x, _ = _seq_data(3, 5, 3, 2)
        y = np.eye(2)[RNG.integers(0, 2, 3)]
        assert check_gradients(net, x, y, subset=50)

    def test_embedding_sequence(self):
        net = _mln(InputType.recurrent(10, 6),
                   EmbeddingSequenceLayer(nIn=10, nOut=4),
                   LSTM(nOut=4),
                   GlobalPoolingLayer(poolingType="PNORM", pnorm=2),
                   OutputLayer(nOut=2, lossFunction="MCXENT"))
        x = RNG.integers(0, 10, (3, 6))
        y = np.eye(2)[RNG.integers(0, 2, 3)]
        assert check_gradients(net, x, y, subset=40)


class TestGraphVertices:
    def _graph(self, add_fn, nin=4, nout=2, n=4):
        g = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
             .graphBuilder().addInputs("in"))
        last = add_fn(g)
        g.addLayer("out", OutputLayer(nIn=None, nOut=nout,
                                      lossFunction="MCXENT"), last)
        g.setOutputs("out")
        g.setInputTypes(InputType.feedForward(nin))
        net = ComputationGraph(g.build()).init()
        x = RNG.normal(size=(n, nin)).astype(np.float64)
        y = np.eye(nout)[RNG.integers(0, nout, n)].astype(np.float64)
        return net, x, y

    def test_merge_vertex(self):
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex

        def build(g):
            g.addLayer("a", DenseLayer(nIn=4, nOut=3, activation="TANH"), "in")
            g.addLayer("b", DenseLayer(nIn=4, nOut=3, activation="SIGMOID"), "in")
            g.addVertex("m", MergeVertex(), "a", "b")
            return "m"

        net, x, y = self._graph(build)
        assert check_gradients_graph(net, x, y, subset=50)

    @pytest.mark.parametrize("op", ["Add", "Product", "Subtract", "Average", "Max"])
    def test_elementwise_vertex(self, op):
        from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex

        def build(g):
            g.addLayer("a", DenseLayer(nIn=4, nOut=3, activation="TANH"), "in")
            g.addLayer("b", DenseLayer(nIn=4, nOut=3, activation="SIGMOID"), "in")
            g.addVertex("e", ElementWiseVertex(op=op), "a", "b")
            return "e"

        net, x, y = self._graph(build)
        assert check_gradients_graph(net, x, y, subset=40)

    def test_scale_shift_l2norm(self):
        from deeplearning4j_tpu.nn.conf.graph import (L2NormalizeVertex,
                                                      ScaleVertex, ShiftVertex)

        def build(g):
            g.addLayer("a", DenseLayer(nIn=4, nOut=3, activation="TANH"), "in")
            g.addVertex("s", ScaleVertex(scaleFactor=1.7), "a")
            g.addVertex("sh", ShiftVertex(shiftFactor=0.3), "s")
            g.addVertex("n", L2NormalizeVertex(), "sh")
            return "n"

        net, x, y = self._graph(build)
        assert check_gradients_graph(net, x, y, subset=40)

    def test_stack_unstack_subset(self):
        from deeplearning4j_tpu.nn.conf.graph import (StackVertex, SubsetVertex,
                                                      UnstackVertex)

        def build(g):
            g.addLayer("a", DenseLayer(nIn=4, nOut=4, activation="TANH"), "in")
            g.addLayer("b", DenseLayer(nIn=4, nOut=4, activation="SIGMOID"), "in")
            g.addVertex("st", StackVertex(), "a", "b")
            g.addVertex("u0", UnstackVertex(fromIndex=0, stackSize=2), "st")
            g.addVertex("sub", SubsetVertex(fromIndex=1, toIndex=2), "u0")
            return "sub"

        net, x, y = self._graph(build)
        assert check_gradients_graph(net, x, y, subset=40)

    def test_attention_vertex_gradcheck(self):
        from deeplearning4j_tpu.nn.conf.graph import AttentionVertex
        g = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
             .graphBuilder().addInputs("seq"))
        g.addVertex("attn", AttentionVertex(nInQueries=3, nInKeys=3, nInValues=3,
                                            nOut=4, nHeads=2), "seq", "seq", "seq")
        g.addLayer("out", RnnOutputLayer(nIn=4, nOut=2, lossFunction="MCXENT"),
                   "attn")
        g.setOutputs("out")
        g.setInputTypes(InputType.recurrent(3, 4))
        net = ComputationGraph(g.build()).init()
        x = RNG.normal(size=(2, 4, 3)).astype(np.float64)
        y = np.eye(2)[RNG.integers(0, 2, (2, 4))].astype(np.float64)
        assert check_gradients_graph(net, x, y, subset=50)
