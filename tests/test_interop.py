"""Interop runtimes: TF GraphRunner (ref: nd4j-tensorflow GraphRunner tests)
and Arrow record conversion (ref: datavec-arrow ArrowConverterTest)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
pa = pytest.importorskip("pyarrow")


def _frozen_mlp_graphdef():
    """A tiny frozen graph: y = relu(x @ W + b), constants baked in."""
    rng = np.random.RandomState(0)
    W = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)

    @tf.function
    def f(x):
        return tf.nn.relu(tf.matmul(x, W) + b, name="y")

    conc = f.get_concrete_function(tf.TensorSpec([None, 4], tf.float32, name="x"))
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), W, b


class TestGraphRunner:
    def test_run_frozen_graph(self):
        from deeplearning4j_tpu.interop import GraphRunner
        gd, W, b = _frozen_mlp_graphdef()
        runner = GraphRunner(gd.SerializeToString(),
                             inputNames=["x"], outputNames=["Identity"])
        x = np.random.RandomState(1).rand(5, 4).astype(np.float32)
        with runner:
            out = runner.run({"x": x})
        expected = np.maximum(x @ W + b, 0)
        np.testing.assert_allclose(out["Identity"], expected, rtol=1e-5)

    def test_autodetect_io(self):
        from deeplearning4j_tpu.interop import GraphRunner
        gd, W, b = _frozen_mlp_graphdef()
        runner = GraphRunner(gd.SerializeToString())
        assert runner.inputNames == ["x"]
        assert len(runner.outputNames) >= 1
        with runner:
            out = runner.run({"x": np.zeros((2, 4), np.float32)})
        # relu(0*W + b) = max(b, 0)
        np.testing.assert_allclose(
            list(out.values())[0], np.tile(np.maximum(b, 0), (2, 1)), rtol=1e-5)

    def test_unknown_feed_raises(self):
        from deeplearning4j_tpu.interop import GraphRunner
        gd, _, _ = _frozen_mlp_graphdef()
        runner = GraphRunner(gd.SerializeToString())
        with pytest.raises(ValueError, match="unexpected input"):
            runner.run({"bogus": np.zeros((1, 4), np.float32)})

    def test_file_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.interop import GraphRunner
        gd, W, b = _frozen_mlp_graphdef()
        p = tmp_path / "frozen.pb"
        p.write_bytes(gd.SerializeToString())
        runner = GraphRunner(str(p), inputNames=["x"])
        x = np.ones((1, 4), np.float32)
        with runner:
            out = runner.run({"x": x})
        np.testing.assert_allclose(
            list(out.values())[0], np.maximum(x @ W + b, 0), rtol=1e-5)


class TestArrowConverter:
    def _schema_and_records(self):
        from deeplearning4j_tpu.datavec import (
            BooleanWritable, DoubleWritable, IntWritable, NullWritable,
            Schema, Text)
        schema = (Schema.Builder()
                  .addColumnDouble("d").addColumnInteger("i")
                  .addColumnString("s").addColumnBoolean("b")
                  .build())
        records = [
            [DoubleWritable(1.5), IntWritable(7), Text("a"), BooleanWritable(True)],
            [DoubleWritable(-2.0), IntWritable(0), Text("bb"), BooleanWritable(False)],
            [NullWritable(), IntWritable(3), Text(""), BooleanWritable(True)],
        ]
        return schema, records

    def test_table_roundtrip(self):
        from deeplearning4j_tpu.datavec import ArrowConverter, NullWritable
        schema, records = self._schema_and_records()
        table = ArrowConverter.toArrowTable(records, schema)
        assert table.num_rows == 3
        assert [f.name for f in table.schema] == ["d", "i", "s", "b"]
        assert str(table.schema.field("d").type) == "double"
        assert str(table.schema.field("i").type) == "int32"
        back = ArrowConverter.fromArrowTable(table)
        assert back[0][0].toDouble() == 1.5
        assert back[1][2].toString() == "bb"
        assert back[2][3].value is True
        assert isinstance(back[2][0], NullWritable)

    def test_schema_from_arrow(self):
        from deeplearning4j_tpu.datavec import ArrowConverter, ColumnType
        schema, records = self._schema_and_records()
        table = ArrowConverter.toArrowTable(records, schema)
        inferred = ArrowConverter.schemaFromArrow(table)
        assert inferred.getColumnNames() == ["d", "i", "s", "b"]
        assert inferred.columns[0].type == ColumnType.Double
        assert inferred.columns[1].type == ColumnType.Integer
        assert inferred.columns[3].type == ColumnType.Boolean

    def test_ipc_file_and_reader(self, tmp_path):
        from deeplearning4j_tpu.datavec import (
            ArrowConverter, ArrowRecordReader, CollectionInputSplit)
        schema, records = self._schema_and_records()
        p = str(tmp_path / "recs.arrow")
        ArrowConverter.writeRecordsToFile(p, records, schema)
        back = ArrowConverter.readRecordsFromFile(p)
        assert len(back) == 3 and back[0][1].toInt() == 7

        reader = ArrowRecordReader()
        reader.initialize(CollectionInputSplit([p]))
        assert reader.schema.getColumnNames() == ["d", "i", "s", "b"]
        rows = []
        while reader.hasNext():
            rows.append(reader.next())
        assert len(rows) == 3
        reader.reset()
        assert reader.hasNext()

    def test_unmappable_column_raises(self):
        from deeplearning4j_tpu.datavec import ArrowConverter, Schema
        from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType
        schema, records = self._schema_and_records()
        bad = Schema([ColumnMeta("nd", ColumnType.NDArray)] )
        with pytest.raises(ValueError, match="no Arrow mapping"):
            ArrowConverter.toArrowTable([[records[0][0]]], bad)
