"""RL module tests (ref: rl4j-core's QLearningDiscreteTest / policy tests —
convergence on small MDPs stands in for rl4j's gym integration tests, which
need an external gym server)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.rl import (
    A2CConfiguration, A2CDiscreteDense, BoltzmannPolicy, CartPole, ChainMDP,
    EpsGreedy, ExpReplay, QLearningConfiguration, QLearningDiscreteDense,
    Transition,
)
from deeplearning4j_tpu.train import Adam


def q_net_conf(obs, n_actions, seed=0):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="RELU"))
            .layer(OutputLayer(nOut=n_actions, activation="IDENTITY",
                               lossFunction="MSE"))
            .setInputType(InputType.feedForward(obs)).build())


def pi_net_conf(obs, n_actions, seed=0):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(3e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="TANH"))
            .layer(OutputLayer(nOut=n_actions, lossFunction="MCXENT"))  # softmax
            .setInputType(InputType.feedForward(obs)).build())


def v_net_conf(obs, seed=1):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(3e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="TANH"))
            .layer(OutputLayer(nOut=1, activation="IDENTITY", lossFunction="MSE"))
            .setInputType(InputType.feedForward(obs)).build())


class TestReplay:
    def test_ring_overwrite_and_sampling(self):
        rep = ExpReplay(max_size=4, obs_size=2, seed=0)
        for i in range(6):
            rep.store(Transition(np.full(2, i, np.float32), i % 2, float(i),
                                 np.full(2, i + 1, np.float32), False))
        assert len(rep) == 4
        obs, actions, rewards, next_obs, dones = rep.sample(16)
        assert obs.shape == (16, 2)
        assert set(np.unique(obs[:, 0])) <= {2, 3, 4, 5}  # 0,1 overwritten


class TestPolicies:
    def test_eps_greedy_anneals(self):
        pol = EpsGreedy(min_epsilon=0.1, anneal_steps=10, seed=0)
        assert pol.epsilon == pytest.approx(1.0)
        for _ in range(10):
            pol.select(np.array([0.0, 1.0]))
        assert pol.epsilon == pytest.approx(0.1)
        # at min epsilon, mostly greedy
        picks = [pol.select(np.array([0.0, 1.0])) for _ in range(100)]
        assert np.mean(picks) > 0.85

    def test_boltzmann_prefers_high_q(self):
        pol = BoltzmannPolicy(temperature=0.5, seed=0)
        picks = [pol.select(np.array([0.0, 2.0])) for _ in range(200)]
        assert np.mean(picks) > 0.9


class TestEnvironments:
    def test_chain_optimal_return(self):
        env = ChainMDP(n_states=5, horizon=10)
        obs = env.reset()
        assert obs.argmax() == 1
        total = 0.0
        for _ in range(10):
            obs, r, done, _ = env.step(1)
            total += r
        assert done and total == pytest.approx(8.0)  # arrives step 3, rewarded steps 3-10

    def test_cartpole_random_falls(self):
        env = CartPole(seed=0)
        env.reset()
        rng = np.random.RandomState(0)
        steps = 0
        done = False
        while not done and steps < 500:
            _, _, done, _ = env.step(int(rng.randint(2)))
            steps += 1
        assert steps < 200  # random policy cannot balance long


class TestQLearning:
    def test_dqn_solves_chain(self):
        env = ChainMDP(n_states=5, horizon=10)
        cfg = QLearningConfiguration(
            seed=0, gamma=0.95, batchSize=32, expRepMaxSize=2000,
            targetDqnUpdateFreq=50, updateStart=50, doubleDQN=True,
            minEpsilon=0.05, epsilonNbStep=400, maxStep=2500, maxEpochStep=10)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        rewards = dqn.train()
        assert len(rewards) == 250  # 2500 steps / 10-step episodes
        # greedy play achieves the optimal return (always right: 8.0)
        assert dqn.play() == pytest.approx(8.0)
        # learned Q ranks 'right' above 'left' in interior states
        for s in range(1, 4):
            obs = np.zeros(5, np.float32)
            obs[s] = 1.0
            q = dqn.q_values(obs)
            assert q[1] > q[0], (s, q)

    def test_vanilla_vs_double_flag(self):
        env = ChainMDP(n_states=4, horizon=8)
        cfg = QLearningConfiguration(seed=1, doubleDQN=False, maxStep=600,
                                     updateStart=40, epsilonNbStep=200,
                                     maxEpochStep=8)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions, 1),
                                     cfg)
        rewards = dqn.train()
        assert np.mean(rewards[-10:]) > np.mean(rewards[:10])

    def test_target_network_lags_online(self):
        env = ChainMDP()
        cfg = QLearningConfiguration(seed=0, targetDqnUpdateFreq=10 ** 9,
                                     maxStep=150, updateStart=32, maxEpochStep=20)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        dqn.train()
        import jax
        online = np.concatenate([np.ravel(l) for l in jax.tree_util.tree_leaves(dqn._params)])
        target = np.concatenate([np.ravel(l) for l in jax.tree_util.tree_leaves(dqn._target)])
        assert not np.allclose(online, target)  # target never synced


class TestA2C:
    def test_a2c_improves_on_chain(self):
        env = ChainMDP(n_states=5, horizon=10)
        cfg = A2CConfiguration(seed=0, gamma=0.95, nStep=16, maxStep=4000,
                               maxEpochStep=10, entropyCoef=0.01)
        a2c = A2CDiscreteDense(env, pi_net_conf(env.obs_size, env.n_actions),
                               v_net_conf(env.obs_size), cfg)
        rewards = a2c.train()
        assert np.mean(rewards[-20:]) > np.mean(rewards[:20])
        assert a2c.play() >= 7.0  # near-optimal greedy rollout


@pytest.mark.slow
class TestCartPoleLearning:
    def test_dqn_improves_cartpole(self):
        env = CartPole(seed=0, max_steps=200)
        cfg = QLearningConfiguration(
            seed=0, gamma=0.99, batchSize=64, expRepMaxSize=10000,
            targetDqnUpdateFreq=200, updateStart=200, doubleDQN=True,
            minEpsilon=0.05, epsilonNbStep=2000, maxStep=8000, maxEpochStep=200)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        rewards = dqn.train()
        early = np.mean(rewards[:10])
        late = np.mean(rewards[-10:])
        assert late > early * 2, (early, late)
