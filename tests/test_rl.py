"""RL module tests (ref: rl4j-core's QLearningDiscreteTest / policy tests —
convergence on small MDPs stands in for rl4j's gym integration tests, which
need an external gym server)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.rl import (
    A2CConfiguration, A2CDiscreteDense, BoltzmannPolicy, CartPole, ChainMDP,
    EpsGreedy, ExpReplay, QLearningConfiguration, QLearningDiscreteDense,
    Transition,
)
from deeplearning4j_tpu.train import Adam


def q_net_conf(obs, n_actions, seed=0):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="RELU"))
            .layer(OutputLayer(nOut=n_actions, activation="IDENTITY",
                               lossFunction="MSE"))
            .setInputType(InputType.feedForward(obs)).build())


def pi_net_conf(obs, n_actions, seed=0):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(3e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="TANH"))
            .layer(OutputLayer(nOut=n_actions, lossFunction="MCXENT"))  # softmax
            .setInputType(InputType.feedForward(obs)).build())


def v_net_conf(obs, seed=1):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(3e-3))
            .list()
            .layer(DenseLayer(nOut=32, activation="TANH"))
            .layer(OutputLayer(nOut=1, activation="IDENTITY", lossFunction="MSE"))
            .setInputType(InputType.feedForward(obs)).build())


class TestReplay:
    def test_ring_overwrite_and_sampling(self):
        rep = ExpReplay(max_size=4, obs_size=2, seed=0)
        for i in range(6):
            rep.store(Transition(np.full(2, i, np.float32), i % 2, float(i),
                                 np.full(2, i + 1, np.float32), False))
        assert len(rep) == 4
        obs, actions, rewards, next_obs, dones = rep.sample(16)
        assert obs.shape == (16, 2)
        assert set(np.unique(obs[:, 0])) <= {2, 3, 4, 5}  # 0,1 overwritten


class TestPolicies:
    def test_eps_greedy_anneals(self):
        pol = EpsGreedy(min_epsilon=0.1, anneal_steps=10, seed=0)
        assert pol.epsilon == pytest.approx(1.0)
        for _ in range(10):
            pol.select(np.array([0.0, 1.0]))
        assert pol.epsilon == pytest.approx(0.1)
        # at min epsilon, mostly greedy
        picks = [pol.select(np.array([0.0, 1.0])) for _ in range(100)]
        assert np.mean(picks) > 0.85

    def test_boltzmann_prefers_high_q(self):
        pol = BoltzmannPolicy(temperature=0.5, seed=0)
        picks = [pol.select(np.array([0.0, 2.0])) for _ in range(200)]
        assert np.mean(picks) > 0.9


class TestEnvironments:
    def test_chain_optimal_return(self):
        env = ChainMDP(n_states=5, horizon=10)
        obs = env.reset()
        assert obs.argmax() == 1
        total = 0.0
        for _ in range(10):
            obs, r, done, _ = env.step(1)
            total += r
        assert done and total == pytest.approx(8.0)  # arrives step 3, rewarded steps 3-10

    def test_cartpole_random_falls(self):
        env = CartPole(seed=0)
        env.reset()
        rng = np.random.RandomState(0)
        steps = 0
        done = False
        while not done and steps < 500:
            _, _, done, _ = env.step(int(rng.randint(2)))
            steps += 1
        assert steps < 200  # random policy cannot balance long


class TestQLearning:
    def test_dqn_solves_chain(self):
        env = ChainMDP(n_states=5, horizon=10)
        cfg = QLearningConfiguration(
            seed=0, gamma=0.95, batchSize=32, expRepMaxSize=2000,
            targetDqnUpdateFreq=50, updateStart=50, doubleDQN=True,
            minEpsilon=0.05, epsilonNbStep=400, maxStep=2500, maxEpochStep=10)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        rewards = dqn.train()
        assert len(rewards) == 250  # 2500 steps / 10-step episodes
        # greedy play achieves the optimal return (always right: 8.0)
        assert dqn.play() == pytest.approx(8.0)
        # learned Q ranks 'right' above 'left' in interior states
        for s in range(1, 4):
            obs = np.zeros(5, np.float32)
            obs[s] = 1.0
            q = dqn.q_values(obs)
            assert q[1] > q[0], (s, q)

    def test_vanilla_vs_double_flag(self):
        env = ChainMDP(n_states=4, horizon=8)
        cfg = QLearningConfiguration(seed=1, doubleDQN=False, maxStep=600,
                                     updateStart=40, epsilonNbStep=200,
                                     maxEpochStep=8)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions, 1),
                                     cfg)
        rewards = dqn.train()
        assert np.mean(rewards[-10:]) > np.mean(rewards[:10])

    def test_target_network_lags_online(self):
        env = ChainMDP()
        cfg = QLearningConfiguration(seed=0, targetDqnUpdateFreq=10 ** 9,
                                     maxStep=150, updateStart=32, maxEpochStep=20)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        dqn.train()
        import jax
        online = np.concatenate([np.ravel(l) for l in jax.tree_util.tree_leaves(dqn._params)])
        target = np.concatenate([np.ravel(l) for l in jax.tree_util.tree_leaves(dqn._target)])
        assert not np.allclose(online, target)  # target never synced


class TestA2C:
    def test_a2c_improves_on_chain(self):
        env = ChainMDP(n_states=5, horizon=10)
        cfg = A2CConfiguration(seed=0, gamma=0.95, nStep=16, maxStep=4000,
                               maxEpochStep=10, entropyCoef=0.01)
        a2c = A2CDiscreteDense(env, pi_net_conf(env.obs_size, env.n_actions),
                               v_net_conf(env.obs_size), cfg)
        rewards = a2c.train()
        assert np.mean(rewards[-20:]) > np.mean(rewards[:20])
        assert a2c.play() >= 7.0  # near-optimal greedy rollout


@pytest.mark.slow
class TestCartPoleLearning:
    def test_dqn_improves_cartpole(self):
        env = CartPole(seed=0, max_steps=200)
        cfg = QLearningConfiguration(
            seed=0, gamma=0.99, batchSize=64, expRepMaxSize=10000,
            targetDqnUpdateFreq=200, updateStart=200, doubleDQN=True,
            minEpsilon=0.05, epsilonNbStep=2000, maxStep=8000, maxEpochStep=200)
        dqn = QLearningDiscreteDense(env, q_net_conf(env.obs_size, env.n_actions),
                                     cfg)
        rewards = dqn.train()
        early = np.mean(rewards[:10])
        late = np.mean(rewards[-10:])
        assert late > early * 2, (early, late)


class TestVectorizedMDP:
    def test_lockstep_and_autoreset(self):
        from deeplearning4j_tpu.rl import VectorizedMDP
        venv = VectorizedMDP([lambda: ChainMDP(n_states=4, horizon=3)
                              for _ in range(3)])
        obs = venv.reset()
        assert obs.shape == (3, 4) and venv.n_actions == 2
        # horizon=3: third step ends every episode and auto-resets
        for t in range(3):
            obs, rewards, dones, infos = venv.step([1, 1, 0])
        assert dones.all()
        assert all("episode_reward" in i for i in infos)
        # auto-reset: obs is the fresh reset state (state index 1)
        assert (obs.argmax(-1) == 1).all()
        # rightward walker reached the end (reward 1 at state 3)
        assert infos[0]["episode_reward"] > infos[2]["episode_reward"]

    def test_truncation_reports_but_not_done(self):
        from deeplearning4j_tpu.rl import VectorizedMDP
        venv = VectorizedMDP([lambda: ChainMDP(n_states=4, horizon=50)])
        venv.reset()
        for _ in range(5):
            obs, rewards, dones, infos = venv.step([1], max_episode_steps=5)
        assert not dones[0]                      # env itself didn't terminate
        assert infos[0]["truncated"] is True     # ...but the limit tripped
        assert "episode_reward" in infos[0]


class TestNStepQ:
    def test_chain_convergence(self):
        """n-step Q over 4 lockstep envs learns the right-moving policy
        (ref: AsyncNStepQLearningDiscreteTest's convergence criterion)."""
        from deeplearning4j_tpu.rl import (
            AsyncNStepQLearningDiscreteDense, AsyncQLearningConfiguration)
        cfg = AsyncQLearningConfiguration(
            seed=3, gamma=0.9, nStep=5, numEnvs=4, targetDqnUpdateFreq=80,
            minEpsilon=0.05, epsilonNbStep=1500, maxStep=4000, maxEpochStep=20)
        learner = AsyncNStepQLearningDiscreteDense(
            lambda: ChainMDP(n_states=5, horizon=20),
            q_net_conf(5, 2, seed=3), cfg)
        rewards = learner.train()
        assert len(rewards) > 20
        # greedy policy walks right and collects the end reward repeatedly
        assert learner.play() > 10.0
        # Q(s, right) > Q(s, left) on interior states
        for s in range(1, 4):
            obs = np.zeros(5, np.float32); obs[s] = 1.0
            q = learner.q_values(obs)
            assert q[1] > q[0], f"state {s}: {q}"


class TestVectorizedA2C:
    def test_a3c_name_and_vector_training(self):
        from deeplearning4j_tpu.rl import A3CConfiguration, A3CDiscreteDense
        assert A3CDiscreteDense is A2CDiscreteDense  # documented sync alias
        cfg = A3CConfiguration(seed=5, gamma=0.9, nStep=8, numEnvs=4,
                               maxStep=4000, maxEpochStep=20)
        learner = A3CDiscreteDense(
            lambda: ChainMDP(n_states=5, horizon=20),
            pi_net_conf(5, 2, seed=5), v_net_conf(5, seed=6), cfg)
        rewards = learner.train()
        assert len(rewards) > 20
        tail = np.mean(rewards[-10:])
        head = np.mean(rewards[:10])
        assert tail > head, f"no improvement: head {head:.2f} tail {tail:.2f}"
        assert learner.play() > 5.0

    def test_single_instance_rejected_for_multi_env(self):
        from deeplearning4j_tpu.rl import A2CConfiguration, A2CDiscreteDense
        with pytest.raises(ValueError, match="factory"):
            A2CDiscreteDense(ChainMDP(), pi_net_conf(6, 2), v_net_conf(6),
                             A2CConfiguration(numEnvs=4))


class TestNStepReturns:
    """Hand-computed cases for the terminal/truncation semantics (the
    cross-reset leak this guards against is invisible to convergence tests)."""

    def test_plain_chain_bootstraps_tail(self):
        from deeplearning4j_tpu.rl.returns import nstep_returns
        S, N, g = 3, 1, 0.5
        rr = np.array([[1.0], [2.0], [4.0]], np.float32)
        no = np.zeros((S, N), bool)
        out = nstep_returns(rr, no, no, np.array([8.0]), np.zeros((S, N)), g)
        # R2 = 4 + .5*8 = 8; R1 = 2 + .5*8 = 6; R0 = 1 + .5*6 = 4
        np.testing.assert_allclose(out[:, 0], [4.0, 6.0, 8.0])

    def test_terminal_zeroes_value_beyond(self):
        from deeplearning4j_tpu.rl.returns import nstep_returns
        rr = np.array([[1.0], [2.0], [4.0]], np.float32)
        dones = np.array([[False], [True], [False]])
        no = np.zeros((3, 1), bool)
        out = nstep_returns(rr, dones, no, np.array([100.0]),
                            np.zeros((3, 1)), 0.5)
        # R1 = 2 (terminal); R0 = 1 + .5*2 = 2; R2 belongs to the NEXT episode
        np.testing.assert_allclose(out[:2, 0], [2.0, 2.0])
        np.testing.assert_allclose(out[2, 0], 4.0 + 0.5 * 100.0)

    def test_truncation_bootstraps_final_obs_not_next_episode(self):
        from deeplearning4j_tpu.rl.returns import nstep_returns
        rr = np.array([[1.0], [2.0], [4.0]], np.float32)
        truncs = np.array([[False], [True], [False]])
        no = np.zeros((3, 1), bool)
        trunc_boot = np.array([[0.0], [10.0], [0.0]], np.float32)
        out = nstep_returns(rr, no, truncs, np.array([100.0]), trunc_boot, 0.5)
        # R1 = 2 + .5*V(final_obs)=7 — NOT chained through R2's episode
        np.testing.assert_allclose(out[1, 0], 7.0)
        np.testing.assert_allclose(out[0, 0], 1.0 + 0.5 * 7.0)
