"""DataVec ETL tests (ref: datavec-api test patterns: reader semantics,
TransformProcess execution + schema evolution + JSON round-trip, record->
DataSet adapters, image pipeline end-to-end into a network)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize)
from deeplearning4j_tpu.datavec import (
    AnalyzeLocal, CollectionRecordReader, CollectionSequenceRecordReader,
    Condition, ConditionFilter, ConditionOp, CSVRecordReader,
    CSVSequenceRecordReader, FileSplit, FilterInvalidValues, ImageRecordReader,
    LineRecordReader, LocalTransformExecutor, MathOp, NumberedFileInputSplit,
    RecordReaderDataSetIterator, RegexLineRecordReader, Schema,
    SequenceRecordReaderDataSetIterator, StringSplit, TransformProcess,
    TransformProcessRecordReader)


CSV = "1.0,2.0,cat,0\n3.0,4.0,dog,1\n5.0,6.0,cat,2\nbad,8.0,fish,0\n"


def _csv_reader(tmp_path, content=CSV, skip=0):
    p = tmp_path / "data.csv"
    p.write_text(content)
    r = CSVRecordReader(skipNumLines=skip)
    r.initialize(FileSplit(str(p)))
    return r


def test_csv_reader_and_splits(tmp_path):
    r = _csv_reader(tmp_path)
    rows = list(r)
    assert len(rows) == 4
    assert rows[0][2].toString() == "cat"
    assert rows[1][0].toDouble() == 3.0
    # reset works
    assert len(list(r)) == 4
    # NumberedFileInputSplit enumerates patterns
    s = NumberedFileInputSplit("f_%d.txt", 2, 5)
    assert s.locations() == ["f_2.txt", "f_3.txt", "f_4.txt", "f_5.txt"]


def test_line_and_regex_readers():
    lr = LineRecordReader()
    lr.initialize(StringSplit("alpha\nbeta\n"))
    assert [r[0].toString() for r in lr] == ["alpha", "beta"]
    rr = RegexLineRecordReader(r"(\d+)-(\w+)")
    rr.initialize(StringSplit("12-ab\n34-cd"))
    out = list(rr)
    assert out[0][0].toString() == "12" and out[1][1].toString() == "cd"


def _schema():
    return (Schema.Builder()
            .addColumnsDouble("a", "b")
            .addColumnCategorical("animal", ["cat", "dog", "fish"])
            .addColumnInteger("label")
            .build())


def test_transform_process_pipeline(tmp_path):
    schema = _schema()
    tp = (TransformProcess.Builder(schema)
          .filter(FilterInvalidValues("a"))                    # drops 'bad' row
          .doubleMathOp("a", MathOp.Multiply, 2.0)
          .categoricalToInteger("animal")
          .removeColumns("b")
          .build())
    rows = list(_csv_reader(tmp_path))
    out = LocalTransformExecutor.execute(rows, tp)
    assert len(out) == 3
    assert [r[0].toDouble() for r in out] == [2.0, 6.0, 10.0]
    assert [r[1].toInt() for r in out] == [0, 1, 0]  # cat,dog,cat
    final = tp.getFinalSchema()
    assert final.getColumnNames() == ["a", "animal", "label"]
    assert final.getType("animal") == "Integer"


def test_transform_one_hot_and_conditional():
    schema = _schema()
    tp = (TransformProcess.Builder(schema)
          .conditionalReplaceValueTransform(
              "a", 0.0, Condition("a", ConditionOp.GreaterThan, 4.0))
          .categoricalToOneHot("animal")
          .build())
    rr = CollectionRecordReader([[1.0, 2.0, "cat", 0], [5.0, 6.0, "fish", 1]])
    out = tp.execute(list(rr))
    assert out[1][0].toDouble() == 0.0          # replaced (5.0 > 4.0)
    assert [w.toInt() for w in out[0][2:5]] == [1, 0, 0]
    assert [w.toInt() for w in out[1][2:5]] == [0, 0, 1]
    assert tp.getFinalSchema().getColumnNames() == [
        "a", "b", "animal[cat]", "animal[dog]", "animal[fish]", "label"]


def test_transform_reduce_and_json_roundtrip():
    schema = (Schema.Builder().addColumnString("key")
              .addColumnsDouble("v").build())
    tp = (TransformProcess.Builder(schema)
          .reduce("key", {"v": "mean"})
          .build())
    rr = CollectionRecordReader([["x", 1.0], ["y", 10.0], ["x", 3.0]])
    out = tp.execute(list(rr))
    assert len(out) == 2
    assert out[0][1].toDouble() == 2.0
    # JSON round-trip preserves behavior (ref: TransformProcess.toJson)
    tp2 = TransformProcess.from_json(tp.to_json())
    rr.reset()
    out2 = tp2.execute(list(rr))
    assert [r[1].toDouble() for r in out2] == [r[1].toDouble() for r in out]


def test_transform_process_record_reader(tmp_path):
    tp = (TransformProcess.Builder(_schema())
          .filter(ConditionFilter(Condition("animal", ConditionOp.InSet,
                                            {"fish"}, numeric=False)))
          .build())
    r = TransformProcessRecordReader(_csv_reader(tmp_path), tp)
    rows = list(r)
    assert len(rows) == 3  # fish row filtered


def test_record_reader_dataset_iterator(tmp_path):
    content = "1,2,0\n3,4,1\n5,6,2\n7,8,1\n"
    r = _csv_reader(tmp_path, content)
    it = RecordReaderDataSetIterator(r, batchSize=3, labelIndex=2, numClasses=3)
    ds = it.next()
    assert ds.features.shape == (3, 2)
    assert ds.labels.shape == (3, 3)
    np.testing.assert_array_equal(ds.labels[1], [0, 1, 0])
    ds2 = it.next()
    assert ds2.features.shape == (1, 2)
    assert not it.hasNext()
    it.reset()
    assert it.hasNext()


def test_sequence_iterator_padding():
    seqs = [[[0.1, 0.2, 0], [0.3, 0.4, 1]],
            [[0.5, 0.6, 2], [0.7, 0.8, 0], [0.9, 1.0, 1]]]
    fr = CollectionSequenceRecordReader(seqs)
    it = SequenceRecordReaderDataSetIterator(fr, miniBatchSize=2,
                                             numPossibleLabels=3, labelIndex=2)
    ds = it.next()
    assert ds.features.shape == (2, 3, 2)   # padded to T=3
    assert ds.labels.shape == (2, 3, 3)
    np.testing.assert_array_equal(ds.features_mask, [[1, 1, 0], [1, 1, 1]])


def test_image_pipeline_end_to_end(tmp_path):
    """PNG files on disk -> ImageRecordReader -> iterator -> LeNet-style net."""
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls in ("zero", "one"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(4):
            arr = rng.integers(0, 255, (12, 12), np.uint8)
            Image.fromarray(arr, "L").save(d / f"{i}.png")
    reader = ImageRecordReader(height=10, width=10, channels=1)
    reader.initialize(FileSplit(str(tmp_path / "imgs"), allowFormats=["png"]))
    assert reader.getLabels() == ["one", "zero"]
    it = RecordReaderDataSetIterator(reader, batchSize=8, labelIndex=1, numClasses=2)
    ds = it.next()
    assert ds.features.shape == (8, 100)  # flattened CHW
    scaler = ImagePreProcessingScaler()
    scaler.transform(ds)
    assert ds.features.max() <= 1.0

    from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(1).list()
            .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3), convolutionMode="Same",
                                    activation="RELU"))
            .layer(OutputLayer(nOut=2, activation="SOFTMAX", lossFunction="MCXENT"))
            .setInputType(InputType.convolutionalFlat(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ds)
    assert np.isfinite(net.score())


def test_analysis_and_normalizers(tmp_path):
    schema = _schema()
    rows = [r for r in _csv_reader(tmp_path)][:3]  # drop bad row
    analysis = AnalyzeLocal.analyze(schema, rows)
    a = analysis.getColumnAnalysis("a")
    assert a.getMin() == 1.0 and a.getMax() == 5.0 and a.getMean() == 3.0

    x = np.array([[0.0, 10.0], [2.0, 20.0], [4.0, 30.0]], np.float32)
    ds = DataSet(x.copy(), x.copy())
    ns = NormalizerStandardize()
    ns.fit(ds)
    ns.transform(ds)
    np.testing.assert_allclose(ds.features.mean(0), 0.0, atol=1e-6)
    ns.revert(ds)
    np.testing.assert_allclose(ds.features, x, atol=1e-5)

    ds2 = DataSet(x.copy(), x.copy())
    mm = NormalizerMinMaxScaler()
    mm.fit(ds2)
    mm.transform(ds2)
    assert ds2.features.min() == 0.0 and ds2.features.max() == 1.0


def test_csv_sequence_reader(tmp_path):
    for i in range(2):
        (tmp_path / f"seq_{i}.csv").write_text("1,2\n3,4\n5,6\n")
    r = CSVSequenceRecordReader()
    r.initialize(NumberedFileInputSplit(str(tmp_path / "seq_%d.csv"), 0, 1))
    seqs = [r.next() for _ in range(2)]
    assert not r.hasNext()
    assert len(seqs[0]) == 3 and seqs[0][2][1].toDouble() == 6.0


def test_sequence_normalizer_masked_nwc():
    """Regression (review): 3D stats are per-FEATURE (NWC) and exclude padding."""
    x1 = np.zeros((1, 3, 2), np.float32)
    x1[0, :2] = [[1.0, 10.0], [3.0, 30.0]]          # third step is padding
    m1 = np.array([[1, 1, 0]], np.float32)
    x2 = np.zeros((1, 5, 2), np.float32)             # different T than batch 1
    x2[0] = [[5.0, 50.0]] * 5
    m2 = np.ones((1, 5), np.float32)
    ds1 = DataSet(x1, x1, features_mask=m1)
    ds2 = DataSet(x2, x2, features_mask=m2)

    class _It:
        def __init__(self):
            self._d = [ds1, ds2]

        def reset(self):
            pass

        def __iter__(self):
            return iter([ds1, ds2])

    ns = NormalizerStandardize()
    ns.fit(_It())
    # 7 unmasked rows: f0 mean = (1+3+5*5)/7
    np.testing.assert_allclose(ns.mean, [(1 + 3 + 25) / 7, (10 + 30 + 250) / 7])
    ns.transform(ds2)
    assert ds2.features.shape == (1, 5, 2)  # broadcast over NWC


def test_csv_blank_lines_skipped(tmp_path):
    r = _csv_reader(tmp_path, "1,2\n\n3,4\n\n")
    assert len(list(r)) == 2


def test_negative_label_raises(tmp_path):
    r = _csv_reader(tmp_path, "1,2,-1\n")
    it = RecordReaderDataSetIterator(r, batchSize=1, labelIndex=2, numClasses=3)
    with pytest.raises(ValueError, match="outside"):
        it.next()


def test_lfw_svhn_iterators():
    """(ref: LFWDataSetIterator / SvhnDataSetIterator) — synthetic surrogate
    shapes + honest flag."""
    from deeplearning4j_tpu.data.fetchers import (
        LFWDataSetIterator, SvhnDataSetIterator)
    lfw = LFWDataSetIterator(batch_size=8, num_examples=32, num_classes=7)
    ds = lfw.next()
    assert np.asarray(ds.features).shape == (8, 3, 64, 64)
    assert np.asarray(ds.labels).shape == (8, 7)
    assert lfw.synthetic is True
    svhn = SvhnDataSetIterator(batch_size=16, num_examples=64, train=False)
    ds = svhn.next()
    assert np.asarray(ds.features).shape == (16, 3, 32, 32)
    assert np.asarray(ds.labels).sum() == 16
