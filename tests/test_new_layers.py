"""Gradient checks + behavior tests for the round-2 layer additions
(ref: GradientCheckTests / CNNGradientCheckTest / AttentionLayerTest /
YoloGradientCheckTests / CapsnetGradientCheckTest — every layer class ships
with a gradcheck tier, SURVEY §4.1)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    AutoEncoder, CapsuleLayer, CapsuleStrengthLayer, CenterLossOutputLayer,
    Convolution3D, ConvolutionLayer, Cropping1D, Cropping3D, DenseLayer,
    ElementWiseMultiplicationLayer, GravesBidirectionalLSTM,
    LearnedSelfAttentionLayer, LocallyConnected1D, LocallyConnected2D,
    LastTimeStep, MaskZeroLayer, OCNNOutputLayer, OutputLayer, PReLULayer,
    PrimaryCapsules, RnnOutputLayer, LSTM, SelfAttentionLayer,
    SpaceToDepthLayer, Subsampling3DLayer, Upsampling1D, Upsampling3D,
    VariationalAutoencoder, Yolo2OutputLayer, ZeroPadding1DLayer,
    ZeroPadding3DLayer, GlobalPoolingLayer,
)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.utils.gradientcheck import check_gradients

RNG = np.random.default_rng(12345)


def _ff_data(n=8, f=6, c=3):
    x = RNG.normal(size=(n, f)).astype(np.float64)
    y = np.eye(c)[RNG.integers(0, c, n)].astype(np.float64)
    return x, y


def _seq_data(n=4, t=5, f=6, c=3):
    x = RNG.normal(size=(n, t, f)).astype(np.float64)
    y = np.eye(c)[RNG.integers(0, c, (n, t))].astype(np.float64)
    return x, y


def _net(*layers, inputType=None, seed=7):
    b = NeuralNetConfiguration.Builder().seed(seed).updater(Adam(0.01)).list()
    for l in layers:
        b = b.layer(l)
    if inputType is not None:
        b = b.setInputType(inputType)
    return MultiLayerNetwork(b.build()).init()


class TestGradientChecks:
    def _check(self, net, x, y, subset=80):
        assert check_gradients(net, x, y, subset=subset), "gradient check failed"

    def test_prelu(self):
        x, y = _ff_data()
        net = _net(DenseLayer(nIn=6, nOut=8),
                   PReLULayer(inputShape=(8,)),
                   OutputLayer(nIn=8, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_elementwise_multiplication(self):
        x, y = _ff_data()
        net = _net(DenseLayer(nIn=6, nOut=8, activation="TANH"),
                   ElementWiseMultiplicationLayer(nIn=8),
                   OutputLayer(nIn=8, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_locally_connected_1d(self):
        x, y = _seq_data(t=6)
        net = _net(LocallyConnected1D(nIn=6, nOut=4, kernelSize=2, inputLength=6,
                                      activation="TANH"),
                   RnnOutputLayer(nIn=4, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, np.eye(3)[RNG.integers(0, 3, (4, 5))].astype(np.float64))

    def test_locally_connected_2d(self):
        x = RNG.normal(size=(4, 2, 6, 6)).astype(np.float64)
        y = np.eye(3)[RNG.integers(0, 3, 4)].astype(np.float64)
        net = _net(LocallyConnected2D(nIn=2, nOut=4, kernelSize=(3, 3),
                                      inputSize=(6, 6), activation="TANH"),
                   OutputLayer(nIn=4 * 4 * 4, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_convolution3d(self):
        x = RNG.normal(size=(2, 2, 4, 4, 4)).astype(np.float64)
        y = np.eye(3)[RNG.integers(0, 3, 2)].astype(np.float64)
        net = _net(Convolution3D(nIn=2, nOut=3, kernelSize=(2, 2, 2),
                                 activation="TANH"),
                   Subsampling3DLayer(kernelSize=(3, 3, 3), stride=(3, 3, 3)),
                   OutputLayer(nIn=3, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_graves_bidirectional_lstm(self):
        x, y = _seq_data()
        net = _net(GravesBidirectionalLSTM(nIn=6, nOut=5),
                   RnnOutputLayer(nIn=5, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_learned_self_attention(self):
        x = RNG.normal(size=(4, 5, 6)).astype(np.float64)
        y = np.eye(3)[RNG.integers(0, 3, 4)].astype(np.float64)
        net = _net(LearnedSelfAttentionLayer(nIn=6, nOut=4, nQueries=2),
                   GlobalPoolingLayer(poolingType="AVG"),
                   OutputLayer(nIn=4, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_recurrent_attention(self):
        from deeplearning4j_tpu.nn.conf.layers import RecurrentAttentionLayer
        x, y = _seq_data()
        net = _net(RecurrentAttentionLayer(nIn=6, nOut=4),
                   RnnOutputLayer(nIn=4, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)

    def test_center_loss_output(self):
        x, y = _ff_data()
        net = _net(DenseLayer(nIn=6, nOut=8, activation="TANH"),
                   CenterLossOutputLayer(nIn=8, nOut=3, lossFunction="MCXENT",
                                         lambda_=0.01))
        self._check(net, x, y)

    def test_capsule_stack(self):
        x = RNG.normal(size=(2, 1, 12, 12)).astype(np.float64)
        y = np.eye(3)[RNG.integers(0, 3, 2)].astype(np.float64)
        net = _net(PrimaryCapsules(channels=2, capsuleDimensions=4,
                                   kernelSize=(5, 5), stride=(4, 4)),
                   CapsuleLayer(capsules=3, capsuleDimensions=4, routings=2),
                   CapsuleStrengthLayer(),
                   OutputLayer(nIn=3, nOut=3, lossFunction="MCXENT"),
                   inputType=InputType.convolutional(12, 12, 1))
        self._check(net, x, y, subset=60)

    def test_autoencoder_supervised_grad(self):
        x, y = _ff_data()
        net = _net(AutoEncoder(nIn=6, nOut=5, activation="SIGMOID",
                               corruptionLevel=0.0),
                   OutputLayer(nIn=5, nOut=3, lossFunction="MCXENT"))
        self._check(net, x, y)


class TestShapesAndBehavior:
    def test_shape_layers_1d_3d(self):
        x = RNG.normal(size=(2, 6, 4)).astype(np.float32)  # (B,T,C)
        for layer, expect in [
            (Upsampling1D(size=2), (2, 12, 4)),
            (Cropping1D(cropping=(1, 2)), (2, 3, 4)),
            (ZeroPadding1DLayer(padding=(2, 1)), (2, 9, 4)),
        ]:
            out, _ = layer.apply({}, jnp.asarray(x))
            assert out.shape == expect, type(layer).__name__

        v = RNG.normal(size=(2, 3, 4, 4, 4)).astype(np.float32)  # NCDHW
        for layer, expect in [
            (Upsampling3D(size=(2, 1, 2)), (2, 3, 8, 4, 8)),
            (Cropping3D(cropping=(1, 1, 0, 1, 1, 0)), (2, 3, 2, 3, 3)),
            (ZeroPadding3DLayer(padding=(1, 0, 0, 0, 2, 0)), (2, 3, 5, 4, 6)),
        ]:
            out, _ = layer.apply({}, jnp.asarray(v))
            assert out.shape == expect, type(layer).__name__

    def test_space_to_depth_layer(self):
        x = jnp.asarray(RNG.normal(size=(2, 3, 4, 4)), jnp.float32)
        out, _ = SpaceToDepthLayer(blockSize=2).apply({}, x)
        assert out.shape == (2, 12, 2, 2)

    def test_mask_zero_layer(self):
        inner = LSTM(nIn=4, nOut=3, weightInit="XAVIER")
        layer = MaskZeroLayer(underlying=inner)
        import jax
        p = layer.init_params(jax.random.key(0))
        x = np.zeros((2, 5, 4), np.float32)
        x[:, :3] = RNG.normal(size=(2, 3, 4))
        out, _ = layer.apply(p, jnp.asarray(x))
        # all-zero (masked) trailing steps freeze the recurrent state
        np.testing.assert_allclose(out[:, 3], out[:, 4], atol=1e-6)

    def test_ocnn_output_trains(self):
        x = RNG.normal(size=(16, 6)).astype(np.float32)
        net = _net(DenseLayer(nIn=6, nOut=8, activation="RELU"),
                   OCNNOutputLayer(nIn=8, hiddenSize=4, nu=0.1))
        y = np.zeros((16, 1), np.float32)  # unused by the one-class loss
        r0 = float(net._params[-1]["r"])
        net.fit(DataSet(x, y), epochs=10)
        assert np.isfinite(net.score())
        # the −r objective term must drive the boundary: if the hinge is the
        # only force, r only ever shrinks and gradients die at loss 0
        assert float(net._params[-1]["r"]) != r0
        # full objective (hinge/nu − r) can go negative; the degenerate
        # hinge-only implementation would pin score at exactly 0 quickly
        assert net.score() != 0.0


class TestPretraining:
    def test_autoencoder_pretrain_reduces_reconstruction(self):
        x = RNG.normal(size=(32, 8)).astype(np.float32)
        net = _net(AutoEncoder(nIn=8, nOut=4, activation="SIGMOID",
                               corruptionLevel=0.1),
                   OutputLayer(nIn=4, nOut=2, lossFunction="MCXENT"))
        ds = DataSet(x, np.zeros((32, 2), np.float32))
        import jax
        layer = net.layers[0]
        before = float(layer.pretrain_loss(net._params[0], jnp.asarray(x),
                                           jax.random.key(1)))
        net.pretrainLayer(0, ds, epochs=30)
        after = float(layer.pretrain_loss(net._params[0], jnp.asarray(x),
                                          jax.random.key(1)))
        assert after < before * 0.9, (before, after)

    def test_vae_pretrain_elbo_improves(self):
        x = RNG.normal(size=(32, 6)).astype(np.float32) * 0.5
        vae = VariationalAutoencoder(nIn=6, nOut=3, encoderLayerSizes=(12,),
                                     decoderLayerSizes=(12,), activation="TANH")
        net = _net(vae, OutputLayer(nIn=3, nOut=2, lossFunction="MCXENT"))
        ds = DataSet(x, np.zeros((32, 2), np.float32))
        import jax
        before = float(vae.pretrain_loss(net._params[0], jnp.asarray(x),
                                         jax.random.key(1)))
        net.pretrainLayer(0, ds, epochs=60)
        after = float(vae.pretrain_loss(net._params[0], jnp.asarray(x),
                                        jax.random.key(1)))
        assert after < before, (before, after)
        # latent forward works for the supervised path
        assert net.output(x).shape == (32, 2)
        # reconstruction probability API
        lp = vae.reconstructionProbability(net._params[0], jnp.asarray(x[:4]))
        assert lp.shape == (4,)

    def test_vae_gradcheck_elbo(self):
        """ELBO gradients (reparameterized sampling with fixed rng) must match
        numerics (ref: VAE gradient checks in BNGradientCheckTest family)."""
        import jax
        from jax.flatten_util import ravel_pytree
        vae = VariationalAutoencoder(nIn=4, nOut=2, encoderLayerSizes=(5,),
                                     decoderLayerSizes=(5,), activation="TANH")
        p = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float64),
            vae.init_params(jax.random.key(3), jnp.float64))
        x = jnp.asarray(RNG.normal(size=(6, 4)), jnp.float64)
        rng = jax.random.key(9)
        flat, unravel = ravel_pytree(p)
        f = lambda fp: vae.pretrain_loss(unravel(fp), x, rng)
        g = jax.grad(f)(flat)
        eps = 1e-6
        idxs = RNG.choice(flat.shape[0], 40, replace=False)
        for i in idxs:
            e = jnp.zeros_like(flat).at[i].set(eps)
            num = (f(flat + e) - f(flat - e)) / (2 * eps)
            assert abs(float(g[i]) - float(num)) < 1e-4 * max(1.0, abs(float(num))), i


class TestYolo:
    def _labels(self, B=2, C=3, H=4, W=4):
        lab = np.zeros((B, 4 + C, H, W), np.float32)
        # one object per image at cell (1,2): offsets .5,.5, size 1.5x2 cells
        for b in range(B):
            lab[b, 0:4, 1, 2] = [0.5, 0.5, 1.5, 2.0]
            lab[b, 4 + (b % C), 1, 2] = 1.0
        return lab

    def test_yolo_loss_decreases_and_decodes(self):
        anchors = ((1.0, 1.0), (2.0, 2.0))
        A, C, H, W = 2, 3, 4, 4
        net = _net(ConvolutionLayer(nIn=2, nOut=A * (5 + C), kernelSize=(1, 1),
                                    activation="IDENTITY"),
                   Yolo2OutputLayer(boundingBoxes=anchors))
        x = RNG.normal(size=(2, 2, H, W)).astype(np.float32)
        lab = self._labels()
        ds = DataSet(x, lab)
        s0 = None
        for _ in range(30):
            net.fit(ds)
            if s0 is None:
                s0 = net.score()
        assert net.score() < s0 * 0.8, (s0, net.score())
        out = net.output(x).toNumpy()
        dets = net.layers[-1].getPredictedObjects(out, threshold=0.3)
        assert len(dets) == 2  # one list per batch item

    def test_yolo_gradcheck(self):
        anchors = ((1.0, 1.0),)
        net = _net(ConvolutionLayer(nIn=1, nOut=1 * (5 + 2), kernelSize=(1, 1),
                                    activation="IDENTITY"),
                   Yolo2OutputLayer(boundingBoxes=anchors))
        x = RNG.normal(size=(2, 1, 3, 3)).astype(np.float64)
        lab = np.zeros((2, 6, 3, 3), np.float64)
        lab[:, 0:4, 1, 1] = [0.4, 0.6, 1.0, 1.0]
        lab[:, 4, 1, 1] = 1.0
        assert check_gradients(net, x, lab, subset=60)


class TestVertices:
    def test_attention_vertex_in_graph(self):
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph import AttentionVertex
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
                .graphBuilder()
                .addInputs("seq")
                .addVertex("attn", AttentionVertex(nInQueries=6, nInKeys=6,
                                                   nInValues=6, nOut=4, nHeads=2),
                           "seq", "seq", "seq")
                .addLayer("out", RnnOutputLayer(nIn=4, nOut=3,
                                                lossFunction="MCXENT"), "attn")
                .setOutputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = RNG.normal(size=(2, 5, 6)).astype(np.float32)
        out = g.output(x)[0]
        assert out.shape == (2, 5, 3)
        y = np.eye(3)[RNG.integers(0, 3, (2, 5))].astype(np.float32)
        g.fit(DataSet(x, y), epochs=3)
        assert np.isfinite(g.score())

    def test_dot_product_attention_vertex(self):
        from deeplearning4j_tpu.nn.conf.graph import DotProductAttentionVertex
        q = jnp.asarray(RNG.normal(size=(2, 3, 4)), jnp.float32)
        kv = jnp.asarray(RNG.normal(size=(2, 5, 4)), jnp.float32)
        out = DotProductAttentionVertex().apply([q, kv, kv])
        assert out.shape == (2, 3, 4)
        # masked keys must get (near-)zero attention weight
        mask = np.ones((2, 5), np.float32)
        mask[:, 3:] = 0.0
        masked = DotProductAttentionVertex().apply([q, kv, kv, jnp.asarray(mask)])
        oracle = DotProductAttentionVertex().apply([q, kv[:, :3], kv[:, :3]])
        np.testing.assert_allclose(np.asarray(masked), np.asarray(oracle), atol=1e-6)

    def test_attention_vertex_with_l2_regularization(self):
        """Vertices in a regularized graph must not crash _loss_for
        (GraphVertex.regularizable defaults to ())."""
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph import AttentionVertex
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01))
                .l2(1e-4)
                .graphBuilder()
                .addInputs("seq")
                .addVertex("attn", AttentionVertex(nInQueries=6, nInKeys=6,
                                                   nInValues=6, nOut=4, nHeads=2),
                           "seq", "seq", "seq")
                .addLayer("out", RnnOutputLayer(nIn=4, nOut=3,
                                                lossFunction="MCXENT"), "attn")
                .setOutputs("out")
                .build())
        g = ComputationGraph(conf).init()
        x = RNG.normal(size=(2, 5, 6)).astype(np.float32)
        y = np.eye(3)[RNG.integers(0, 3, (2, 5))].astype(np.float32)
        g.fit(DataSet(x, y), epochs=2)
        assert np.isfinite(g.score())

    def test_preprocessor_vertex(self):
        from deeplearning4j_tpu.nn.conf.graph import PreprocessorVertex
        x = jnp.asarray(RNG.normal(size=(2, 3, 4, 4)), jnp.float32)
        out = PreprocessorVertex(preprocessor="cnnToFF").apply([x])
        assert out.shape == (2, 48)


def test_json_roundtrip_new_layers():
    """Every new layer class must survive config JSON round-trip (ref:
    the reference's Jackson serde invariant, SURVEY §5.6)."""
    from deeplearning4j_tpu.nn.conf.layers import Layer
    layers = [
        PReLULayer(inputShape=(4,)),
        ElementWiseMultiplicationLayer(nIn=4),
        MaskZeroLayer(underlying=LSTM(nIn=4, nOut=3)),
        SpaceToDepthLayer(blockSize=2),
        Upsampling1D(size=3), Upsampling3D(size=(2, 2, 2)),
        Cropping1D(cropping=(1, 1)), Cropping3D(),
        ZeroPadding1DLayer(padding=(1, 2)), ZeroPadding3DLayer(),
        Convolution3D(nIn=2, nOut=4), Subsampling3DLayer(),
        LocallyConnected1D(nIn=3, nOut=4, inputLength=7),
        LocallyConnected2D(nIn=3, nOut=4, inputSize=(5, 5)),
        AutoEncoder(nIn=6, nOut=3),
        VariationalAutoencoder(nIn=6, nOut=3, encoderLayerSizes=(7,)),
        CenterLossOutputLayer(nIn=4, nOut=3),
        OCNNOutputLayer(nIn=4, hiddenSize=3),
        Yolo2OutputLayer(boundingBoxes=((1.0, 2.0),)),
        GravesBidirectionalLSTM(nIn=4, nOut=3),
        LearnedSelfAttentionLayer(nIn=4, nOut=3, nQueries=2),
        PrimaryCapsules(channels=2), CapsuleLayer(capsules=3),
        CapsuleStrengthLayer(),
    ]
    for l in layers:
        d = l.to_dict()
        l2 = Layer.from_dict(d)
        assert type(l2) is type(l), type(l).__name__
        assert l2.to_dict() == d, type(l).__name__
