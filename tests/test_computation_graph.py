"""ComputationGraph tests (ref: org.deeplearning4j.nn.graph.ComputationGraph
test patterns: vertex semantics, DAG training, config JSON round-trip,
MLN-equivalence for a linear graph)."""
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import (
    ComputationGraph, ComputationGraphConfiguration, ElementWiseVertex,
    InputType, MergeVertex, MultiLayerNetwork, NeuralNetConfiguration,
    ScaleVertex, StackVertex, SubsetVertex, UnstackVertex)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train.updaters import Adam, Sgd


def _xor_data():
    x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    return x, y


def test_linear_graph_matches_mln():
    """A linear DAG must train identically to the equivalent MultiLayerNetwork
    (same seed => same init => same trajectory)."""
    x, y = _xor_data()
    mln_conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.5))
                .list()
                .layer(DenseLayer(nIn=2, nOut=8, activation="TANH"))
                .layer(OutputLayer(nIn=8, nOut=2, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .build())
    mln = MultiLayerNetwork(mln_conf).init()

    g_conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.5))
              .graphBuilder()
              .addInputs("in")
              .addLayer("h", DenseLayer(nIn=2, nOut=8, activation="TANH"), "in")
              .addLayer("out", OutputLayer(nIn=8, nOut=2, activation="SOFTMAX",
                                           lossFunction="MCXENT"), "h")
              .setOutputs("out")
              .build())
    cg = ComputationGraph(g_conf).init()

    for _ in range(50):
        mln.fit(x, y)
        cg.fit(x, y)
    np.testing.assert_allclose(mln.score(DataSet(x, y)), cg.score(DataSet(x, y)),
                               rtol=1e-5)
    np.testing.assert_allclose(mln.output(x).toNumpy(),
                               cg.outputSingle(x).toNumpy(), atol=1e-5)


def test_merge_and_elementwise_vertices():
    x, y = _xor_data()
    conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(0.05))
            .graphBuilder()
            .addInputs("in")
            .addLayer("a", DenseLayer(nIn=2, nOut=4, activation="RELU"), "in")
            .addLayer("b", DenseLayer(nIn=2, nOut=4, activation="TANH"), "in")
            .addVertex("merge", MergeVertex(), "a", "b")          # (B, 8)
            .addVertex("sum", ElementWiseVertex(op="Add"), "a", "b")
            .addVertex("scaled", ScaleVertex(scaleFactor=0.5), "sum")
            .addVertex("merge2", MergeVertex(), "merge", "scaled")  # (B, 12)
            .addLayer("out", OutputLayer(nOut=2, activation="SOFTMAX",
                                         lossFunction="MCXENT"), "merge2")
            .setOutputs("out")
            .build())
    # nIn auto-filled through the vertex chain
    assert conf.nodes[-1].op.nIn == 12
    cg = ComputationGraph(conf).init()
    for _ in range(200):
        cg.fit(x, y)
    ev_out = cg.outputSingle(x).toNumpy()
    assert (np.argmax(ev_out, 1) == np.argmax(y, 1)).all()


def test_multi_input_multi_output():
    rng = np.random.default_rng(0)
    xa = rng.normal(size=(16, 3)).astype(np.float32)
    xb = rng.normal(size=(16, 5)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    y2 = rng.normal(size=(16, 1)).astype(np.float32)
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(0.01))
            .graphBuilder()
            .addInputs("ina", "inb")
            .addLayer("ha", DenseLayer(nIn=3, nOut=8, activation="RELU"), "ina")
            .addLayer("hb", DenseLayer(nIn=5, nOut=8, activation="RELU"), "inb")
            .addVertex("m", MergeVertex(), "ha", "hb")
            .addLayer("cls", OutputLayer(nOut=2, activation="SOFTMAX",
                                         lossFunction="MCXENT"), "m")
            .addLayer("reg", OutputLayer(nOut=1, activation="IDENTITY",
                                         lossFunction="MSE"), "m")
            .setOutputs("cls", "reg")
            .build())
    cg = ComputationGraph(conf).init()
    mds = MultiDataSet([xa, xb], [y1, y2])
    s0 = None
    for _ in range(50):
        cg.fit(mds)
        if s0 is None:
            s0 = cg.score()
    assert cg.score() < s0
    outs = cg.output(xa, xb)
    assert outs[0].shape == (16, 2) and outs[1].shape == (16, 1)


def test_stack_unstack_subset():
    conf = (NeuralNetConfiguration.Builder().seed(1)
            .graphBuilder()
            .addInputs("a", "b")
            .addVertex("st", StackVertex(), "a", "b")
            .addVertex("u0", UnstackVertex(fromIndex=0, stackSize=2), "st")
            .addVertex("u1", UnstackVertex(fromIndex=1, stackSize=2), "st")
            .addVertex("sub", SubsetVertex(fromIndex=1, toIndex=2), "u1")
            .addLayer("out", OutputLayer(nIn=2, nOut=2, activation="IDENTITY",
                                         lossFunction="MSE"), "sub")
            .setOutputs("out")
            .build())
    cg = ComputationGraph(conf).init()
    a = np.arange(8, dtype=np.float32).reshape(2, 4)
    b = -np.arange(8, dtype=np.float32).reshape(2, 4)
    acts = cg.feedForward(a, b)
    np.testing.assert_array_equal(acts["st"].toNumpy(),
                                  np.concatenate([a, b], axis=0))
    np.testing.assert_array_equal(acts["u0"].toNumpy(), a)
    np.testing.assert_array_equal(acts["u1"].toNumpy(), b)
    np.testing.assert_array_equal(acts["sub"].toNumpy(), b[:, 1:3])


def test_graph_json_roundtrip():
    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(0.01))
            .graphBuilder()
            .addInputs("in")
            .addLayer("h", DenseLayer(nIn=4, nOut=6, activation="RELU"), "in")
            .addVertex("sc", ScaleVertex(scaleFactor=2.0), "h")
            .addLayer("out", OutputLayer(nIn=6, nOut=3, activation="SOFTMAX",
                                         lossFunction="MCXENT"), "sc")
            .setOutputs("out")
            .build())
    js = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(js)
    assert conf2.to_json() == js
    # restored conf is runnable and numerically identical (same seed)
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    o1 = ComputationGraph(conf).init().outputSingle(x).toNumpy()
    o2 = ComputationGraph(conf2).init().outputSingle(x).toNumpy()
    np.testing.assert_allclose(o1, o2, atol=1e-6)
