"""Headline benchmark: BERT-base masked-LM training throughput on one chip,
plus a continuous-batching decode leg (serving/generation.py).

Mirrors BASELINE.json's metric ("SameDiff BERT-base tokens/sec/chip"): the
reference runs this workload through the SameDiff op-by-op JVM interpreter;
here it is one fused XLA executable (fwd+bwd+AdamW, bf16 compute, no remat —
activations fit HBM at bench shapes and recompute cost ~15% throughput).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "decode",
"availability"}. ``vs_baseline`` is measured MFU / 0.35 (the north-star
gate from BASELINE.json) since the reference publishes no in-tree numbers
(SURVEY.md §6, BASELINE "published": {}). ``decode`` reports the
GenerationEngine's steady-state numbers: decode tokens/sec across all
slots, median time-to-first-token, slot occupancy at steady state, the
compiled-signature count (must stay ≤ prefill ladder + 1), the paged
KV-cache capacity roll-up (HBM bytes per resident stream vs the
contiguous layout, block utilization) and the ``shared_prefix``
scenario (N streams over one registered prefix — one prefill total).
``availability`` is the resilience leg: success rate and p99 latency under
a fixed seeded FaultPlan injecting 5% transient dispatch failures.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _peak_flops(device) -> float:
    from deeplearning4j_tpu.profiler.profiler import peak_flops
    return peak_flops(device)


def main():
    from deeplearning4j_tpu.models import (
        TransformerConfig, init_params, make_train_step)

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        # BERT-base 12L/768H/12 heads/512 seq. remat off: activations fit a
        # single chip's HBM at B=48 and recompute costs ~15% throughput
        # (measured: 117k tok/s no-remat vs 100k dots-remat vs 96k full).
        # attention_impl='flash' routes to the packed whole-head VMEM Pallas
        # kernel (fwd+bwd on-chip, no (T,T) HBM traffic, no head
        # transposes) — the round-4 lever that broke the round-2/3 HBM
        # plateau (tools/profile_flagship.py: the XLA attention score path
        # was 67 ms of the 182 ms step). softmax stays fp32: the kernel's
        # bf16 p_dtype saves VPU time standalone but the full step hides it
        # under DMA (measured parity), so exactness is free. B=96: with the
        # kernel, throughput rises past the old B=48 plateau (B sweep:
        # 48 -> 163k, 96 -> 172k, 128 -> 160k).
        cfg = TransformerConfig(remat=False, attention_impl="flash")
        B, T, steps, warmup = 96, 512, 10, 3
    else:                                   # CPU smoke fallback (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2, heads=4,
                                mlp_dim=512, max_seq=128, dtype=jnp.float32,
                                remat=False)
        B, T, steps, warmup = 8, 128, 3, 1

    key = jax.random.PRNGKey(0)
    params = init_params(key, cfg)
    init_state, step = make_train_step(cfg, learning_rate=1e-4)
    opt_state = init_state(params)

    rng = np.random.default_rng(0)
    def make_batch():
        return {
            "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
            "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
            "weights": jnp.ones((B, T), jnp.float32),
        }

    batch = make_batch()
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch)
    # NB: under the axon tunnel block_until_ready is a no-op; a host transfer
    # is the only reliable synchronization point.
    float(loss)

    # median of 3 timing windows: the axon tunnel adds sporadic per-window
    # latency (~±3% observed); the median is the honest steady-state number
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
        float(loss)
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[1]

    tokens_per_sec = B * T * steps / dt

    # MFU on the repo-wide single basis (profiler.MFU_BASIS): analytic model
    # flops, no remat recompute at bench config. XLA-counted flops for the
    # same step live in the committed profile artifact as mfu_xla
    # (tools/profile_flagship.py).
    from deeplearning4j_tpu.profiler.profiler import (
        MFU_BASIS, mfu as _mfu, non_embedding_params,
        transformer_flops_per_token)
    flops_per_token = transformer_flops_per_token(
        non_embedding_params(params, cfg), cfg.layers, cfg.hidden, T)
    peak = _peak_flops(jax.devices()[0]) if on_tpu else 1e12
    mfu = _mfu(tokens_per_sec, flops_per_token, peak)

    print(json.dumps({
        "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec",
        "mfu": round(mfu, 4),
        "mfu_basis": MFU_BASIS,
        "vs_baseline": round(mfu / 0.35, 4),
        "vs_baseline_basis": "mfu / 0.35 north-star gate (BASELINE.json)",
        "decode": decode_leg(on_tpu),
        "availability": availability_leg(on_tpu),
        "observability": observability_leg(on_tpu),
        "fairness": fairness_leg(on_tpu),
        "cluster": cluster_leg(on_tpu),
        "soak": soak_leg(on_tpu),
    }))


def decode_leg(on_tpu: bool) -> dict:
    """Continuous-batching decode throughput: saturate every slot of one
    GenerationEngine with staggered prompts (the ORCA regime — admissions
    and retirements interleave with decode iterations) and report the
    scheduler's sustained rate. Decode tokens/sec is summed across slots:
    one decode_step samples a token for EVERY live slot, which is exactly
    why iteration-level scheduling wins over request-level batching.

    The KV roll-up is the paged-cache capacity story (vLLM SOSP'23): a
    resident stream holds ceil((len+max_new)/block) blocks instead of a
    worst-case max_len row, so at the contiguous layout's HBM budget the
    pool seats `resident_streams_at_contiguous_budget` streams — the
    chat-shaped prompt mix (lengths well under max_len) is where paging
    earns its keep. `shared_prefix` is the CoW scenario: N streams over
    one 256-token registered prefix, ONE prefix prefill total."""
    from deeplearning4j_tpu.models import (
        TransformerConfig, init_params)
    from deeplearning4j_tpu.serving import GenerationEngine

    if on_tpu:
        cfg = TransformerConfig(causal=True, remat=False,
                                attention_impl="flash")
        slots, max_len, n_requests, max_new = 16, 512, 48, 64
    else:                                   # CPU smoke (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2,
                                heads=4, mlp_dim=512, max_seq=128,
                                dtype=jnp.float32, causal=True, remat=False)
        slots, max_len, n_requests, max_new = 4, 64, 8, 12

    params = init_params(jax.random.PRNGKey(0), cfg)
    with GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                          queue_capacity=n_requests + slots) as eng:
        stats, paged_stream_bytes = _run_decode_mix(eng, cfg, n_requests,
                                                    max_new)
        from deeplearning4j_tpu.serving import kv_bytes_per_token
        itemsize = jnp.dtype(cfg.dtype).itemsize
        contig_stream_bytes = max_len * kv_bytes_per_token(
            cfg.layers, cfg.heads, cfg.head_dim, "float32", itemsize)
        measured = paged_stream_bytes is not None
        return {
            **stats,
            "slots": slots,
            "requests": n_requests,
            "max_new_tokens": max_new,
            "block_size": eng.block_size,
            "kv_blocks_total": eng._allocator.capacity,
            "steady_state_block_utilization": round(
                stats["steady_state_blocks_in_use"]
                / eng._allocator.capacity, 4),
            "kv_bytes_per_stream_contiguous": contig_stream_bytes,
            "kv_bytes_per_stream_ratio": round(
                paged_stream_bytes / contig_stream_bytes, 4)
                if measured else None,
            "resident_streams_at_contiguous_budget": int(
                slots * contig_stream_bytes // paged_stream_bytes)
                if measured else None,
            "paged_grid": paged_decode_grid(on_tpu),
            "speculative": speculative_grid(on_tpu),
            "shared_prefix": shared_prefix_scenario(on_tpu),
            "occupancy": occupancy_leg(on_tpu),
        }


def _run_decode_mix(eng, cfg, n_requests: int, max_new: int):
    """THE decode measurement harness, shared by :func:`decode_leg` and
    every :func:`paged_decode_grid` cell so the two can never drift:
    warm the engine, reset metrics (warmup's samples include the
    one-time XLA compiles, which would swamp the steady-state numbers —
    the engine is idle here, so the swap cannot race a live stream),
    submit the seeded chat-shaped mix (prompts well under max_len: the
    regime where block-granular storage beats worst-case reservation),
    sample the occupancy/block gauges while the backlog drains (sampling
    at submit time would race the scheduler's admissions; first sample
    unconditional — on a device fast enough to drain before the first
    5 ms poll the loop body would never run and the capacity numbers
    would be built from nothing), and join every stream.

    Returns ``(stats, stream_bytes)`` — the common steady-state dict
    plus HBM bytes per resident stream, ``None`` when unmeasured (all
    samples post-drain): better no number than a 0-byte stream or an
    absurd streams-at-budget figure."""
    from deeplearning4j_tpu.serving import ServingMetrics

    eng.warmup()
    eng.metrics = ServingMetrics()
    eng.metrics.kv_blocks_total.set(eng._allocator.capacity)
    rng = np.random.default_rng(0)       # same mix for every caller
    t0 = time.perf_counter()
    handles = []
    for _ in range(n_requests):
        n = int(rng.integers(4, max(5, eng.max_len // 4)))
        handles.append(eng.submit(
            rng.integers(0, cfg.vocab_size, n).astype(np.int32),
            max_new_tokens=max_new))
    occ_samples, blk_samples = [], []
    while True:
        occ_samples.append(eng.metrics.slot_occupancy.value)
        blk_samples.append(eng.metrics.kv_blocks_in_use.value)
        if handles[-1].future.done():
            break
        time.sleep(0.005)
    for h in handles:
        h.result(timeout=600)
    wall_s = time.perf_counter() - t0
    m = eng.metrics
    occ = float(np.median(occ_samples))
    blocks_in_use = float(np.median(blk_samples))
    resident = occ * eng.slots
    measured = blocks_in_use > 0 and resident > 0
    stream_bytes = (blocks_in_use * eng.kv_block_bytes / resident
                    if measured else None)
    stats = {
        "decode_tokens_per_sec": round(m.decode_tokens_per_sec(), 2),
        "end_to_end_tokens_per_sec": round(
            n_requests * max_new / wall_s, 2),
        "ttft_ms_p50": round(m.ttft_ms.quantile(0.5), 3),
        "decode_step_ms_p50": round(m.decode_step_ms.quantile(0.5), 3),
        "steady_state_slot_occupancy": round(occ, 3),
        "compiled_signatures": eng.compiled_signatures(),
        "signature_bound": len(eng.buckets) + 1,
        "steady_state_blocks_in_use": round(blocks_in_use, 1),
        "kv_hbm_bytes_per_resident_stream":
            round(stream_bytes) if measured else None,
    }
    return stats, stream_bytes


def paged_decode_grid(on_tpu: bool) -> dict:
    """The decode hot-path grid (ROADMAP 1b/1c + 3b/3c): the SAME
    staggered prompt mix through {gather, fused} attention x {float32,
    int8} KV storage. ``gather`` materializes pool[tables] in HBM every
    step (the PR 6 route); ``fused`` streams blocks through VMEM via the
    Pallas paged-attention kernel, never building the (slots, L) view.
    int8 quantizes on write / dequantizes in the read, shrinking the
    per-stream KV footprint — ``resident_streams_at_contiguous_budget``
    is the capacity headline: how many streams fit the contiguous
    full-precision layout's HBM budget *in the model's cache dtype*
    (the int8 cells compound the dtype ratio — ~3.8x vs fp32 storage,
    ~1.9x vs bf16 — with block granularity, which is how the >=2x ISSUE
    acceptance gate clears under either storage dtype). Tokens/sec and
    TTFT p50 are reported at the fixed occupancy the shared mix
    produces, so the four cells are directly comparable."""
    from deeplearning4j_tpu.models import TransformerConfig, init_params
    from deeplearning4j_tpu.serving import (
        GenerationEngine, kv_bytes_per_token)

    if on_tpu:
        cfg = TransformerConfig(causal=True, remat=False,
                                attention_impl="flash")
        slots, max_len, n_requests, max_new = 16, 512, 32, 64
    else:                                   # CPU smoke (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2,
                                heads=4, mlp_dim=512, max_seq=128,
                                dtype=jnp.float32, causal=True, remat=False)
        slots, max_len, n_requests, max_new = 2, 64, 4, 6

    params = init_params(jax.random.PRNGKey(0), cfg)
    itemsize = jnp.dtype(cfg.dtype).itemsize
    contig_stream_bytes = max_len * kv_bytes_per_token(
        cfg.layers, cfg.heads, cfg.head_dim, "float32", itemsize)

    def cell(kv_dtype: str, paged_attention: str) -> dict:
        with GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                              kv_dtype=kv_dtype,
                              paged_attention=paged_attention,
                              queue_capacity=n_requests + slots) as eng:
            stats, stream_bytes = _run_decode_mix(eng, cfg, n_requests,
                                                  max_new)
            return {
                "kv_dtype": kv_dtype,
                "paged_attention": paged_attention,
                **stats,
                "kv_block_bytes": eng.kv_block_bytes,
                "resident_streams_at_contiguous_budget": int(
                    slots * contig_stream_bytes // stream_bytes)
                    if stream_bytes is not None else None,
            }

    grid = [cell(kv, pa) for kv in ("float32", "int8")
            for pa in ("gather", "fused")]
    return {
        "slots": slots, "max_len": max_len, "requests": n_requests,
        "max_new_tokens": max_new,
        "kv_bytes_per_stream_contiguous_fp": contig_stream_bytes,
        "cells": grid,
    }


def speculative_grid(on_tpu: bool) -> dict:
    """Speculative decoding tokens/sec vs k (ISSUE 17): the SAME staggered
    mix as :func:`paged_decode_grid`, through k in {0, 2, 4, 8} x {gather,
    fused} x {float32, int8}. k=0 is the plain engine (``speculative=
    None``) — the per-(route, dtype) baseline the k>0 cells must beat.

    The draft is a 1-layer model at half the target's width, so its
    per-proposal cost is a fraction of a target decode step — the real
    deployment economics. To pin the acceptance regime the grid zeroes
    ``lm_head`` in BOTH models: logits are identically 0, greedy sampling
    picks the same argmax on both sides, and acceptance is deterministically
    1.0 — the ceiling cells show the pure scheduling win (one verify
    commits k tokens), while ``acceptance_rate`` in each cell keeps the
    headline honest about the regime it was measured in. Determinism means
    the grid needs no warm-up repetitions to be reproducible."""
    from deeplearning4j_tpu.models import TransformerConfig, init_params
    from deeplearning4j_tpu.serving import GenerationEngine, SpecConfig

    if on_tpu:
        cfg = TransformerConfig(causal=True, remat=False,
                                attention_impl="flash")
        dcfg = TransformerConfig(hidden=cfg.hidden // 2, layers=1,
                                 heads=cfg.heads, mlp_dim=cfg.mlp_dim // 2,
                                 vocab_size=cfg.vocab_size,
                                 max_seq=cfg.max_seq, causal=True,
                                 remat=False, attention_impl="flash")
        slots, max_len, n_requests, max_new = 16, 512, 32, 64
    else:                                   # CPU smoke (driver runs TPU)
        # the draft/target cost gap is the whole economics: a 1-layer
        # thin draft against a deep target, so k cheap proposals replace
        # k expensive decode dispatches with ONE (k+1)-position verify
        cfg = TransformerConfig(vocab_size=1024, hidden=256, layers=4,
                                heads=4, mlp_dim=1024, max_seq=128,
                                dtype=jnp.float32, causal=True, remat=False)
        dcfg = TransformerConfig(vocab_size=1024, hidden=32, layers=1,
                                 heads=2, mlp_dim=64, max_seq=128,
                                 dtype=jnp.float32, causal=True,
                                 remat=False)
        slots, max_len, n_requests, max_new = 2, 64, 4, 24

    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    # acceptance-1.0 regime: identical (zero) logits on both sides
    params = {**params, "lm_head": jnp.zeros_like(params["lm_head"])}
    dparams = {**dparams, "lm_head": jnp.zeros_like(dparams["lm_head"])}

    def cell(k: int, kv_dtype: str, paged_attention: str) -> dict:
        spec = SpecConfig(dparams, dcfg, k=k) if k > 0 else None
        with GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                              kv_dtype=kv_dtype,
                              paged_attention=paged_attention,
                              queue_capacity=n_requests + slots,
                              speculative=spec) as eng:
            stats, _ = _run_decode_mix(eng, cfg, n_requests, max_new)
            m = eng.metrics
            return {
                "k": k, "kv_dtype": kv_dtype,
                "paged_attention": paged_attention,
                "tokens_per_sec": stats["end_to_end_tokens_per_sec"],
                "decode_steps_total": m.decode_steps_total.value,
                "acceptance_rate": round(m.spec_acceptance_rate.value, 4)
                    if k > 0 else None,
                "compiled_signatures": stats["compiled_signatures"],
                "signature_bound": len(eng.buckets) + (2 if k > 0 else 1),
                "draft_compiled_signatures":
                    eng.draft_compiled_signatures(),
            }

    grid = [cell(k, kv, pa) for kv in ("float32", "int8")
            for pa in ("gather", "fused") for k in (0, 2, 4, 8)]
    # the ISSUE acceptance gate: at least one k>0 cell beats its own
    # (route, dtype) k=0 baseline on tokens/sec at high acceptance
    base = {(c["kv_dtype"], c["paged_attention"]): c["tokens_per_sec"]
            for c in grid if c["k"] == 0}
    speedups = [round(c["tokens_per_sec"]
                      / base[(c["kv_dtype"], c["paged_attention"])], 3)
                for c in grid if c["k"] > 0]
    return {
        "slots": slots, "max_len": max_len, "requests": n_requests,
        "max_new_tokens": max_new,
        "draft": {"hidden": dcfg.hidden, "layers": dcfg.layers,
                  "mlp_dim": dcfg.mlp_dim},
        "cells": grid,
        "best_speedup_vs_k0": max(speedups) if speedups else None,
    }


def occupancy_leg(on_tpu: bool) -> dict:
    """KV occupancy → 1.0 (ISSUE 13): the SAME chat-shaped mix — a
    shared system prompt plus short unique suffixes, generation budgets
    well past the prompt — through ``allocate="reserve"`` (worst-case
    reservation up front, the pre-existing default) and
    ``allocate="on_demand"`` + the automatic prefix cache (lazy
    per-boundary allocation, QoS-aware preemption with
    recompute-on-resume, retired full blocks reused with no API
    opt-in). Both cells run int8 KV storage, so the on-demand cell
    COMPOUNDS with the PR 9 dtype lever: ``kv_reservation_slack`` is
    the idle tail reserve pays and on-demand recovers,
    ``preemptions_per_1k_tokens`` the recompute price of running the
    pool near occupancy 1.0, ``prefix_cache_hit_rate`` the free
    admissions shared system prompts get, and
    ``resident_streams_at_contiguous_budget`` the capacity headline on
    the same contiguous-fp32-budget basis as the decode grid (the ISSUE
    acceptance gate: >= 1.5x the grid's int8 reserve figure)."""
    from deeplearning4j_tpu.models import TransformerConfig, init_params
    from deeplearning4j_tpu.serving import (
        GenerationEngine, ServingMetrics, blocks_for_tokens,
        kv_bytes_per_token)

    if on_tpu:
        cfg = TransformerConfig(causal=True, remat=False,
                                attention_impl="flash")
        slots, max_len, block, n_requests = 16, 512, 16, 48
        sys_len, sfx_hi, max_new, cache_blocks = 64, 16, 192, 64
    else:                                   # CPU smoke (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2,
                                heads=4, mlp_dim=512, max_seq=128,
                                dtype=jnp.float32, causal=True, remat=False)
        slots, max_len, block, n_requests = 4, 64, 8, 16
        sys_len, sfx_hi, max_new, cache_blocks = 16, 8, 24, 8
    # pool deliberately SMALLER than slots * worst-case: reserve can
    # only seat slots-1 streams at once, on_demand seats every slot and
    # preempts when the pool runs dry — the occupancy-1.0 regime under
    # test, where preemptions/1k-tokens prices the recompute debt
    num_blocks = (slots - 1) * blocks_for_tokens(
        sys_len + sfx_hi + max_new, block) + 1

    params = init_params(jax.random.PRNGKey(0), cfg)
    itemsize = jnp.dtype(cfg.dtype).itemsize
    contig_stream_bytes = max_len * kv_bytes_per_token(
        cfg.layers, cfg.heads, cfg.head_dim, "float32", itemsize)

    def cell(allocate: str, prefix_cache_blocks: int) -> dict:
        with GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                              block_size=block, num_blocks=num_blocks,
                              kv_dtype="int8", allocate=allocate,
                              prefix_cache_blocks=prefix_cache_blocks,
                              queue_capacity=n_requests + slots) as eng:
            eng.warmup()
            eng.metrics = ServingMetrics()  # exclude warmup compiles
            eng.metrics.kv_blocks_total.set(eng._allocator.capacity)
            rng = np.random.default_rng(0)  # same mix in both cells
            sysp = rng.integers(0, cfg.vocab_size, sys_len)
            handles = []
            t0 = time.perf_counter()
            for _ in range(n_requests):
                sfx = rng.integers(0, cfg.vocab_size,
                                   int(rng.integers(2, sfx_hi)))
                handles.append(eng.submit(
                    np.concatenate([sysp, sfx]).astype(np.int32),
                    max_new_tokens=max_new, eos_id=None))
            occ, blk, slack, socc, cblk = [], [], [], [], []
            steady = []
            while True:
                sample = (eng.metrics.kv_block_occupancy.value,
                          eng.metrics.kv_blocks_in_use.value,
                          eng.metrics.kv_reservation_slack.value,
                          eng.metrics.slot_occupancy.value,
                          eng.metrics.prefix_cache_blocks.value)
                for xs, v in zip((occ, blk, slack, socc, cblk), sample):
                    xs.append(v)
                if eng.queue_depth > 0 and sample[3] > 0:
                    # TRUE steady state: every seat contested (a backlog
                    # exists) — drain-edge samples with idling slots
                    # would skew the per-stream footprint
                    steady.append(sample)
                if handles[-1].future.done():
                    break
                time.sleep(0.005)
            if len(steady) >= 3:
                occ, blk, slack, socc, cblk = (list(x)
                                               for x in zip(*steady))
            for h in handles:
                h.result(timeout=600)
            wall_s = time.perf_counter() - t0
            m = eng.metrics
            blocks_in_use = float(np.median(blk))
            resident = float(np.median(socc)) * slots
            tokens_out = m.generated_tokens_total.value
            # per-stream attribution excludes blocks held ONLY by the
            # automatic prefix cache: they are reclaimable-on-demand
            # shared capacity (evicted the moment a stream needs them),
            # not residency — the same reason kv_blocks_usable ignores
            # them in the heartbeat
            stream_blocks = max(0.0, blocks_in_use - float(np.median(cblk)))
            stream_bytes = None
            if stream_blocks > 0 and resident > 0:
                stream_bytes = stream_blocks * eng.kv_block_bytes \
                    / resident
            return {
                "allocate": allocate,
                "prefix_cache_blocks": prefix_cache_blocks,
                "steady_state_pool_occupancy": round(
                    float(np.median(occ)), 4),
                "steady_state_blocks_in_use": round(blocks_in_use, 1),
                "kv_reservation_slack_blocks": round(
                    float(np.median(slack)), 1),
                "preemptions": int(m.preemptions_total.value),
                "preemptions_per_1k_tokens": round(
                    1e3 * m.preemptions_total.value / tokens_out, 3)
                    if tokens_out else None,
                "prefix_cache_hits": int(m.prefix_cache_hits_total.value),
                "prefix_cache_hit_rate": round(
                    m.prefix_cache_hits_total.value / n_requests, 3),
                "decode_tokens_per_sec": round(
                    m.decode_tokens_per_sec(), 2),
                "end_to_end_tokens_per_sec": round(
                    n_requests * max_new / wall_s, 2),
                "kv_hbm_bytes_per_resident_stream":
                    round(stream_bytes) if stream_bytes else None,
                "resident_streams_at_contiguous_budget": int(
                    slots * contig_stream_bytes // stream_bytes)
                    if stream_bytes else None,
                "compiled_signatures": eng.compiled_signatures(),
                "signature_bound": len(eng.buckets) + 1,
            }

    reserve = cell("reserve", 0)
    on_demand = cell("on_demand", cache_blocks)
    r0 = reserve.get("resident_streams_at_contiguous_budget")
    r1 = on_demand.get("resident_streams_at_contiguous_budget")
    return {
        "slots": slots, "max_len": max_len, "block_size": block,
        "requests": n_requests, "system_prompt_tokens": sys_len,
        "max_new_tokens": max_new,
        "kv_bytes_per_stream_contiguous_fp": contig_stream_bytes,
        "reserve": reserve,
        "on_demand": on_demand,
        "on_demand_vs_reserve_streams_ratio": (
            round(r1 / r0, 3) if r0 and r1 else None),
    }


def shared_prefix_scenario(on_tpu: bool) -> dict:
    """Copy-on-write prefix reuse: N streams share ONE registered
    prefix (a 256-token system prompt at TPU scale). The prefix is
    prefilled exactly once — every stream references its pinned blocks
    (the partial tail block via CoW) and feeds only its short suffix
    through the decode executable, so TTFT stops paying the long-prefix
    prefill N times and the pool stops storing it N times."""
    from deeplearning4j_tpu.models import (
        TransformerConfig, init_params)
    from deeplearning4j_tpu.serving import GenerationEngine, ServingMetrics

    if on_tpu:
        cfg = TransformerConfig(causal=True, remat=False,
                                attention_impl="flash")
        slots, max_len, n_streams = 16, 512, 48
        prefix_len, suffix_len, max_new = 256, 8, 32
    else:                                   # CPU smoke (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2,
                                heads=4, mlp_dim=512, max_seq=128,
                                dtype=jnp.float32, causal=True, remat=False)
        slots, max_len, n_streams = 8, 128, 32
        prefix_len, suffix_len, max_new = 64, 4, 8

    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, prefix_len).astype(np.int32)
    with GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                          queue_capacity=n_streams + slots) as eng:
        eng.warmup()
        eng.metrics = ServingMetrics()      # exclude warmup compiles
        t0 = time.perf_counter()
        pid = eng.register_prefix(prefix)
        handles = [eng.submit(
            rng.integers(0, cfg.vocab_size, suffix_len).astype(np.int32),
            prefix_id=pid, max_new_tokens=max_new)
            for _ in range(n_streams)]
        for h in handles:
            h.result(timeout=600)
        wall_s = time.perf_counter() - t0
        m = eng.metrics
        itemsize = jnp.dtype(cfg.dtype).itemsize
        kv_unit = cfg.layers * 2 * cfg.heads * cfg.head_dim * itemsize
        return {
            "streams": n_streams,
            "prefix_tokens": prefix_len,
            "suffix_tokens": suffix_len,
            "max_new_tokens": max_new,
            "prefix_prefills": int(m.prefix_prefills_total.value),
            "stream_prefills": int(m.prefills_total.value),
            "one_prefill_for_all_streams":
                int(m.prefix_prefills_total.value) == 1
                and int(m.prefills_total.value) == 0,
            "prefix_hits": int(m.prefix_hits_total.value),
            "cow_copies": int(m.kv_cow_copies_total.value),
            "ttft_ms_p50": round(m.ttft_ms.quantile(0.5), 3),
            "end_to_end_tokens_per_sec": round(
                n_streams * max_new / wall_s, 2),
            "prefix_kv_bytes_stored_once": prefix_len * kv_unit,
            "prefix_kv_bytes_without_sharing":
                n_streams * prefix_len * kv_unit,
        }


def _tiny_mlp_adapter():
    """Tiny jitted row-wise model shared by the availability and
    observability legs: both measure the serving machinery around the
    dispatch, not the network."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.serving import ModelAdapter

    class _Mlp(ModelAdapter):
        def __init__(self):
            import jax
            super().__init__(model=None)
            w = jax.random.normal(jax.random.PRNGKey(0), (16, 16),
                                  jnp.float32)
            self._fn = jax.jit(lambda x: jnp.tanh(x @ w))

        def infer(self, x):
            return np.asarray(self._fn(jnp.asarray(x, jnp.float32)))

    return _Mlp()


def availability_leg(on_tpu: bool) -> dict:
    """Availability under injected faults: drive the batching engine with a
    fixed seeded FaultPlan failing 5% of ``engine.dispatch`` calls
    transiently, and report the success rate and p99 latency the retry
    layer sustains. The plan is seeded, so this leg is the same fault
    schedule on every run — a regression here is a resilience regression,
    not noise. (The train/decode legs above run with NO plan installed,
    which is the FaultPlan-inactive overhead condition: one global read
    per dispatch.)"""
    from deeplearning4j_tpu.serving import (
        FaultPlan, InferenceEngine, RetryPolicy)

    n_requests = 400 if on_tpu else 120
    fault_rate = 0.05
    # 5% Bernoulli background failures PLUS fixed early call indices: the
    # dispatch count varies with coalescing, so the at= anchors guarantee
    # the retry path is exercised every run (>=15 dispatches at
    # max_batch_size=8 for 120 single-row requests)
    plan = (FaultPlan(seed=0)
            .fail("engine.dispatch", rate=fault_rate)
            .fail("engine.dispatch", at=(1, 3, 7, 11)))
    with InferenceEngine(
            _tiny_mlp_adapter(), max_batch_size=8, max_wait_ms=1.0,
            retry_policy=RetryPolicy(max_attempts=4, base_delay_ms=0.5,
                                     max_delay_ms=8.0, seed=0),
            name="availability") as eng:
        eng.warmup(np.zeros(16, np.float32))
        from deeplearning4j_tpu.serving import ServingMetrics
        eng.metrics = ServingMetrics()   # exclude warmup compiles from p99
        rng = np.random.default_rng(0)
        ok = 0
        with plan:
            futures = [eng.submit(
                rng.standard_normal((1, 16)).astype(np.float32))
                       for _ in range(n_requests)]
            for f in futures:
                try:
                    f.result(timeout=120)
                    ok += 1
                except Exception:
                    pass
        m = eng.metrics
        return {
            "injected_fault_rate": fault_rate,
            "injection_point": "engine.dispatch",
            "requests": n_requests,
            "success_rate": round(ok / n_requests, 4),
            "latency_ms_p99": round(m.latency_ms.quantile(0.99), 3),
            "retries": int(m.retries_total.value),
            "faults_fired": len(plan.fired()),
            "breaker_state": eng.breaker.state,
        }


def observability_leg(on_tpu: bool) -> dict:
    """Tracing overhead: the same seeded traffic through one batching
    engine with request tracing OFF (the default — the zero-allocation
    NULL_TRACE fast path) and again at 100% tail-sampling retention, so
    the "zero cost when off / cheap when on" claim is a tracked number.
    Reports throughput and p99 latency for both conditions plus the
    throughput delta; ``overhead_pct_throughput`` should sit within noise
    of zero for the off condition to hold (it is measured against the
    SAME workload as the PR 3 availability leg, minus the fault plan)."""
    from deeplearning4j_tpu.serving import (
        InferenceEngine, ServingMetrics, Tracer)

    n_requests = 400 if on_tpu else 120

    def run(tracer):
        # median of 3 windows per condition, and max_wait_ms=0 (greedy
        # batch sealing): with a batching window, tiny producer-side
        # timing shifts change how requests coalesce and the window
        # lottery swamps the ~10 us/request tracing cost this leg exists
        # to measure
        with InferenceEngine(
                _tiny_mlp_adapter(), max_batch_size=8, max_wait_ms=0.0,
                queue_capacity_rows=n_requests + 8, tracer=tracer,
                name="observability") as eng:
            eng.warmup(np.zeros(16, np.float32))
            rng = np.random.default_rng(0)
            xs = [rng.standard_normal((1, 16)).astype(np.float32)
                  for _ in range(n_requests)]
            dts = []
            for _ in range(3):
                eng.metrics = ServingMetrics()  # exclude warmup compiles
                t0 = time.perf_counter()
                futures = [eng.submit(x) for x in xs]
                for f in futures:
                    f.result(timeout=120)
                dts.append(time.perf_counter() - t0)
            dt = sorted(dts)[1]
            return {
                "requests_per_sec": round(n_requests / dt, 2),
                "latency_ms_p99": round(
                    eng.metrics.latency_ms.quantile(0.99), 3),
            }

    # alternate conditions and keep each condition's best window: the
    # first engine of the process pays one-time thread/allocator warmup
    # that would otherwise be billed to whichever condition ran first
    tracer = Tracer(sample_rate=1.0, capacity=3 * n_requests)
    off, on = run(None), run(tracer)
    off2, on2 = run(None), run(tracer)
    if off2["requests_per_sec"] > off["requests_per_sec"]:
        off = off2
    if on2["requests_per_sec"] > on["requests_per_sec"]:
        on = on2
    return {
        "requests": n_requests,
        "sampling_off": off,
        "sampling_100": on,
        "overhead_pct_throughput": round(
            (off["requests_per_sec"] - on["requests_per_sec"])
            / off["requests_per_sec"] * 100.0, 2),
        "traces_retained": tracer.stats()["retained"],
        "cross_host": _cross_host_tracing_cell(n_requests),
        "planner_cost_model": _planner_cost_model_cell(),
    }


def _cross_host_tracing_cell(n_requests: int) -> dict:
    """Cross-host stitched tracing overhead (ISSUE 19): the same seeded
    traffic through a 2-host loopback cluster front door with tracing
    OFF (the default — no trace context even built) and at 100%
    sampling with per-host tracers, wire-v3 context propagation, and
    the aggregator's stitched view. ``overhead_us_per_request`` should
    hold the single-host ~10 us/request envelope plus the one
    dict-kwarg hop per dispatch; the off condition must sit within
    noise of the plain engine path (it IS the plain path: NULL_TRACE
    means zero extra kwargs touch the wire)."""
    from deeplearning4j_tpu.serving import (
        ClusterDirectory, ClusterFrontDoor, ClusterStatsAggregator,
        HeartbeatPump, InferenceEngine, LoopbackHost, LoopbackTransport,
        Tracer)

    def run(traced):
        cap = 3 * n_requests
        fd_tracer = Tracer(sample_rate=1.0, capacity=cap) if traced \
            else None
        d = ClusterDirectory(heartbeat_timeout_s=60.0)
        engines, hosts = [], []
        for i in range(2):
            ekw = ({"tracer": Tracer(sample_rate=1.0, capacity=cap)}
                   if traced else {})
            eng = InferenceEngine(
                _tiny_mlp_adapter(), max_batch_size=8, max_wait_ms=0.0,
                queue_capacity_rows=n_requests + 8,
                name=f"xhost-{'on' if traced else 'off'}{i}", **ekw)
            eng.warmup(np.zeros(16, np.float32))
            h = LoopbackHost(i, engine=eng, **ekw)
            d.join(h)
            HeartbeatPump(h, LoopbackTransport(d)).pump_once()
            engines.append(eng)
            hosts.append(h)
        fd = ClusterFrontDoor(d, tracer=fd_tracer)
        try:
            rng = np.random.default_rng(0)
            xs = [rng.standard_normal((1, 16)).astype(np.float32)
                  for _ in range(n_requests)]
            dts = []
            for _ in range(3):
                t0 = time.perf_counter()
                for f in [fd.submit(x) for x in xs]:
                    f.result(timeout=120)
                dts.append(time.perf_counter() - t0)
            dt = sorted(dts)[1]
            out = {"requests_per_sec": round(n_requests / dt, 2)}
            if traced:
                agg = ClusterStatsAggregator(d, hosts=hosts)
                agg.estimate_clock_offsets()
                stitched = agg.stitched_traces()
                out["stitched_traces"] = len(stitched)
                out["multi_span"] = sum(
                    1 for s in stitched if s["span_count"] >= 2)
            return out, dt
        finally:
            for h in hosts:
                h.shutdown()

    (off, dt_off), (on, dt_on) = run(False), run(True)
    return {
        "requests": n_requests,
        "hosts": 2,
        "sampling_off": off,
        "sampling_100_stitched": on,
        "overhead_us_per_request": round(
            (dt_on - dt_off) / n_requests * 1e6, 2),
        "single_host_envelope_us": 10.0,
    }


def _planner_cost_model_cell() -> dict:
    """Cost-model fit quality (ISSUE 19 / ROADMAP 4b): seeded synthetic
    fleet telemetry with a KNOWN tokens/sec curve plus noise, fitted by
    ``fit_cost_models`` exactly the way the elasticity planner does —
    headline numbers are the recovered full-occupancy rate vs ground
    truth and whether the planner's decision log cites the fitted
    cost-per-token (the join/drain unit-economics citation)."""
    from deeplearning4j_tpu.serving import (
        ElasticityPlanner, TimeSeriesStore, config_key)

    true_at_full = 80.0    # rate = 100 - 20*occ
    rng = np.random.default_rng(0)
    ts = TimeSeriesStore()
    for i in range(64):
        occ = float(rng.uniform(0.05, 1.0))
        ts.record(0, {
            "t": float(i),
            "slot_occupancy": occ,
            "tokens_per_sec": 100.0 - 20.0 * occ
            + float(rng.normal(0.0, 2.0)),
            "host_class": "decode",
        })
    planner = ElasticityPlanner(timeseries=ts)
    dec = planner.observe({
        "fleet": {"hosts": 1, "alive": 1, "draining": 0,
                  "slots": 8, "free_slots": 4},
        "hosts": {}, "front_doors": []})
    key = config_key("decode", None)
    m = dec["cost_model"]["models"][key]
    return {
        "samples": 64,
        "true_tokens_per_sec_at_full": true_at_full,
        "fitted_tokens_per_sec_at_full": round(
            m["tokens_per_sec_at_full"], 2),
        "fit_error_pct": round(
            abs(m["tokens_per_sec_at_full"] - true_at_full)
            / true_at_full * 100.0, 2),
        "r2": round(m["r2"], 4),
        "cost_per_token_host_s": m["cost_per_token"],
        "decision_cites_cost_per_token":
            "fitted cost/token" in dec["reason"],
    }


def fairness_leg(on_tpu: bool) -> dict:
    """Multi-tenant QoS under contention (serving/qos.py), three scenarios:

    - ``noisy_neighbor``: one flooding batch-class tenant + one
      interactive tenant against a max_batch_size=1 engine (every
      dispatch serves exactly one request, so QUEUE order is the whole
      story). With QoS off the victim's requests sit behind the flood
      (FIFO); with QoS on the interactive class strictly overtakes.
      Reports the victim's p99 and per-tenant goodput both ways.
    - ``weighted_share``: two batch-class tenants at weights 3:1 drain a
      pre-loaded queue; the first-40-completions split is the measured
      goodput ratio (the ISSUE acceptance number: ~3x +/- 20%).
    - ``retry_storm``: a seeded FaultPlan fails 40% of dispatches
      transiently; amplification = (dispatches incl. retries) /
      dispatches, with and without a deployment RetryBudget — the budget
      caps the storm near 1 + ratio while the un-budgeted run amplifies
      toward the retry limit."""
    import threading

    from deeplearning4j_tpu.serving import (
        FaultPlan, InferenceEngine, QosPolicy, RetryBudget, RetryPolicy,
        TenantPolicy)

    row = np.zeros((1, 16), np.float32)

    # ---------------------------------------------------- noisy neighbor
    def run_noisy(qos):
        """One flooding batch tenant keeps a 256-request queue saturated
        for the whole measurement; the interactive victim submits
        blocking requests THROUGH the contention. FIFO makes each victim
        request drain the whole backlog first; QoS lets it overtake."""
        victim_n = 30
        backlog = 128
        stop = threading.Event()
        with InferenceEngine(
                _tiny_mlp_adapter(), max_batch_size=1, max_wait_ms=0.0,
                queue_capacity_rows=2 * backlog, qos=qos,
                name="fairness") as eng:
            eng.warmup(np.zeros(16, np.float32))

            def flood():
                # keep `backlog` requests queued at all times (half the
                # capacity, so the victim's own submit always admits and
                # the comparison isolates QUEUE ORDER, not entry races)
                outstanding = []
                while not stop.is_set():
                    outstanding = [f for f in outstanding if not f.done()]
                    while len(outstanding) < backlog:
                        try:
                            outstanding.append(
                                eng.submit(row, tenant="noisy",
                                           priority="batch"))
                        except Exception:
                            break
                    time.sleep(0.0005)
                for f in outstanding:
                    try:
                        f.result(timeout=300)
                    except Exception:
                        pass

            ft = threading.Thread(target=flood)
            ft.start()
            time.sleep(0.05)   # flood reaches steady saturation
            lat = []
            t_run = time.perf_counter()
            for _ in range(victim_n):
                t0 = time.perf_counter()
                eng.submit(row, tenant="victim",
                           priority="interactive").result(timeout=120)
                lat.append((time.perf_counter() - t0) * 1e3)
            stop.set()
            ft.join(timeout=300)
            dt = time.perf_counter() - t_run
            lat.sort()
            qs = eng.metrics.qos_snapshot()
            served = {t: d["served"] for t, d in qs["tenants"].items()}
            return {
                "victim_p50_ms": round(lat[len(lat) // 2], 3),
                "victim_p99_ms": round(lat[-1], 3),
                # run durations differ (the victim finishes ~25x sooner
                # with QoS on), so goodput is rate-normalized
                "goodput_per_sec": {t: round(v / dt, 1)
                                    for t, v in served.items()},
                "served": served,
            }

    noisy_policy = QosPolicy({
        "noisy": TenantPolicy(weight=1.0, priority="batch"),
        "victim": TenantPolicy(weight=1.0, priority="interactive")})
    noisy = {"qos_off": run_noisy(None), "qos_on": run_noisy(noisy_policy)}

    # ---------------------------------------------------- weighted share
    heavy_w, light_w = 3.0, 1.0
    pol = QosPolicy({"heavy": TenantPolicy(weight=heavy_w, priority="batch"),
                     "light": TenantPolicy(weight=light_w, priority="batch")})
    order = []
    with InferenceEngine(_tiny_mlp_adapter(), max_batch_size=1,
                         max_wait_ms=0.0, queue_capacity_rows=4096,
                         qos=pol, name="wfq") as eng:
        eng.warmup(np.zeros(16, np.float32))
        plan = FaultPlan(seed=0).delay("engine.dispatch", ms=120, at=(0,))
        with plan:
            futs = [eng.submit(row, tenant="light")]   # wedges dispatch 0
            time.sleep(0.03)
            for _ in range(60):
                for t in ("heavy", "light"):
                    f = eng.submit(row, tenant=t)
                    f.add_done_callback(
                        lambda _f, t=t: order.append(t))
                    futs.append(f)
            for f in futs:
                f.result(timeout=300)
    head = order[:40]
    n_heavy, n_light = head.count("heavy"), head.count("light")
    weighted = {
        "weights": {"heavy": heavy_w, "light": light_w},
        "first_40_completions": {"heavy": n_heavy, "light": n_light},
        "goodput_ratio": round(n_heavy / max(n_light, 1), 3),
    }

    # ------------------------------------------------------- retry storm
    def run_storm(budget):
        n = 120
        plan = (FaultPlan(seed=7)
                .fail("engine.dispatch", rate=0.4))
        with InferenceEngine(
                _tiny_mlp_adapter(), max_batch_size=1, max_wait_ms=0.0,
                queue_capacity_rows=n + 8,
                retry_policy=RetryPolicy(max_attempts=4, base_delay_ms=0.2,
                                         max_delay_ms=2.0, seed=0),
                retry_budget=budget, name="storm") as eng:
            eng.warmup(np.zeros(16, np.float32))
            ok = 0
            with plan:
                futs = [eng.submit(row) for _ in range(n)]
                for f in futs:
                    try:
                        f.result(timeout=120)
                        ok += 1
                    except Exception:
                        pass
            m = eng.metrics
            batches = m.batches_total.value + m.failed_total.value
            retries = m.retries_total.value
            return {
                "requests": n,
                "success_rate": round(ok / n, 4),
                "retries": int(retries),
                "amplification": round((batches + retries)
                                       / max(batches, 1), 4),
                "retry_budget_exhausted":
                    int(m.retry_budget_exhausted_total.value),
            }

    storm = {
        "injected_fault_rate": 0.4,
        "budget_off": run_storm(None),
        "budget_on": run_storm(RetryBudget(ratio=0.1, burst=5.0)),
    }

    return {"noisy_neighbor": noisy, "weighted_share": weighted,
            "retry_storm": storm}


def cluster_leg(on_tpu: bool) -> dict:
    """Pod-slice control-plane leg (serving/cluster.py): (a) 1-host vs
    3-host loopback throughput scaling through the ClusterFrontDoor —
    dispatch cost is a simulated per-batch device time so host
    parallelism, not numpy, is what scales; (b) routed TTFT p50 for
    generation streams fanned over a 3-host loopback cluster (submit ->
    first token through the front door, routing overhead included);
    (c) shed-reason mix under a one-host-degraded scenario: host 0's
    deployment breaker trips and its heartbeat dies, the fleet keeps
    serving via the survivors, and forced sheds type as
    cluster_capacity/host_unavailable in the front door's counters."""
    import time as _time

    from deeplearning4j_tpu.serving import (
        ClusterDirectory, ClusterFrontDoor, HeartbeatPump, InferenceEngine,
        LoopbackHost, LoopbackTransport, ModelAdapter)

    class _SimDevice(ModelAdapter):
        """Fixed 2 ms per dispatched batch (sleep releases the GIL), so
        N hosts serve N batches concurrently — the scaling signal."""

        def __init__(self):
            super().__init__(model=None)
            self.w = np.linspace(-1, 1, 16, dtype=np.float32).reshape(16, 1)

        def infer(self, x):
            _time.sleep(0.002)
            return np.asarray(x) @ self.w

    def make_fleet(n, queue_capacity_rows=4096):
        d = ClusterDirectory(heartbeat_timeout_s=5.0)
        hosts, pumps, engines = [], [], []
        for i in range(n):
            eng = InferenceEngine(_SimDevice(), max_batch_size=8,
                                  max_wait_ms=0.0,
                                  queue_capacity_rows=queue_capacity_rows,
                                  name=f"bench-h{i}")
            h = LoopbackHost(i, engine=eng)
            d.join(h)
            pumps.append(HeartbeatPump(h, LoopbackTransport(d)))
            hosts.append(h)
            engines.append(eng)
        for p in pumps:
            p.pump_once()
        return d, hosts, pumps, engines

    def run_throughput(n_hosts, n_requests=300):
        d, hosts, pumps, engines = make_fleet(n_hosts)
        try:
            fd = ClusterFrontDoor(d)
            x = np.ones((8, 16), np.float32)   # one full bucket per req
            fd.output(x)                        # warm the path
            t0 = _time.perf_counter()
            futs = [fd.submit(x) for _ in range(n_requests)]
            for f in futs:
                f.result(timeout=120)
            dt = _time.perf_counter() - t0
            return n_requests / dt
        finally:
            for h in hosts:
                h.shutdown()

    rps1 = run_throughput(1)
    rps3 = run_throughput(3)

    # ---- routed TTFT p50: generation streams over a 3-host fleet ------
    from deeplearning4j_tpu.models import TransformerConfig, init_params
    from deeplearning4j_tpu.serving import GenerationEngine

    if on_tpu:
        gcfg = TransformerConfig(causal=True, remat=False,
                                 attention_impl="flash")
        slots, max_len, n_streams, max_new = 8, 512, 24, 32
    else:
        gcfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2,
                                 heads=4, mlp_dim=512, max_seq=128,
                                 dtype=jnp.float32, causal=True,
                                 remat=False)
        slots, max_len, n_streams, max_new = 2, 64, 9, 8

    gparams = init_params(jax.random.PRNGKey(0), gcfg)
    d = ClusterDirectory(heartbeat_timeout_s=5.0)
    ghosts, gpumps = [], []
    for i in range(3):
        g = GenerationEngine(gparams, gcfg, slots=slots, max_len=max_len,
                             queue_capacity=n_streams + slots,
                             name=f"bench-g{i}")
        h = LoopbackHost(i, generation=g)
        d.join(h)
        gpumps.append(HeartbeatPump(h, LoopbackTransport(d)))
        ghosts.append(h)
    for p in gpumps:
        p.pump_once()
    try:
        fd = ClusterFrontDoor(d)
        rng = np.random.default_rng(0)
        # warm every host's executables out of the TTFT measurement
        warm = [fd.submit_generate(
            rng.integers(1, gcfg.vocab_size, 8).astype(np.int32),
            max_new_tokens=2, host=i) for i in range(3)]
        for h in warm:
            h.result(timeout=600)
        ttfts = []
        handles = []
        for _ in range(n_streams):
            first = {"t": None}
            t0 = _time.perf_counter()

            def on_token(_tok, first=first, t0=t0):
                if first["t"] is None:
                    first["t"] = (_time.perf_counter() - t0) * 1e3

            handles.append((first, fd.submit_generate(
                rng.integers(1, gcfg.vocab_size, 12).astype(np.int32),
                max_new_tokens=max_new, on_token=on_token)))
        for first, h in handles:
            h.result(timeout=600)
            if first["t"] is not None:
                ttfts.append(first["t"])
        routed_ttft_p50 = float(np.median(ttfts)) if ttfts else None
        gen_routed = fd.routed_by_host.to_dict()
    finally:
        for h in ghosts:
            h.shutdown()

    # ---- one-host-degraded shed mix -----------------------------------
    clock = [0.0]
    d = ClusterDirectory(heartbeat_timeout_s=1.0, probe_interval_s=100.0,
                         clock=lambda: clock[0])
    hosts, pumps, engines = [], [], []
    for i in range(3):
        eng = InferenceEngine(_SimDevice(), max_batch_size=8,
                              max_wait_ms=0.0, queue_capacity_rows=1024,
                              name=f"deg-h{i}")
        h = LoopbackHost(i, engine=eng)
        d.join(h)
        pumps.append(HeartbeatPump(h, LoopbackTransport(d)))
        hosts.append(h)
        engines.append(eng)
    for p in pumps:
        p.pump_once()
    try:
        fd = ClusterFrontDoor(d)
        # degrade host 0: breaker OPEN + heartbeat death
        for _ in range(engines[0].breaker.failure_threshold):
            engines[0].breaker.record_failure()
        clock[0] += 2.0
        for p in pumps[1:]:
            p.pump_once()
        ok = shed = 0
        x = np.ones((8, 16), np.float32)
        futs = []
        for i in range(120):
            try:
                # a third of the burst is pinned to the dead host — the
                # traffic that WOULD have landed there sheds typed
                futs.append(fd.submit(x, host=0 if i % 3 == 0 else None))
            except Exception:
                shed += 1
        for f in futs:
            try:
                f.result(timeout=120)
                ok += 1
            except Exception:
                shed += 1
        degraded = {
            "requests": 120,
            "served": ok,
            "shed": shed,
            "shed_reasons": fd.metrics.rejections_by_reason.to_dict(),
            "routed_by_host": fd.routed_by_host.to_dict(),
            "survivor_share": round(
                (fd.routed_by_host.get("h1")
                 + fd.routed_by_host.get("h2")) / max(ok, 1), 4),
        }
    finally:
        for h in hosts:
            h.shutdown()

    return {
        "throughput_rps_1host": round(rps1, 2),
        "throughput_rps_3host": round(rps3, 2),
        "scaling_3host": round(rps3 / rps1, 4) if rps1 else None,
        "routed_ttft_p50_ms": round(routed_ttft_p50, 3)
            if routed_ttft_p50 is not None else None,
        "gen_routed_by_host": gen_routed,
        "one_host_degraded": degraded,
        "rpc": rpc_subleg(on_tpu, gcfg, gparams, slots, max_len),
        "recovery": recovery_subleg(on_tpu, gcfg, gparams),
        "disagg": disagg_subleg(on_tpu, gcfg, gparams, slots, max_len),
    }


def recovery_subleg(on_tpu: bool, gcfg, gparams) -> dict:
    """Recovery sub-leg (ISSUE 15 — make host loss and preemption
    cheap), two claims measured:

    (a) **resume vs replay.** A lost stream re-dispatched with its
    delivered-so-far watermark costs ONE recompute prefill plus only
    the REMAINING decode steps; a from-zero replay re-decodes
    everything. Measured as the same request finished from its halfway
    watermark vs restarted cold.

    (b) **swap vs recompute preemption.** The identical QoS preemption
    scenario (batch victim evicted for an interactive aggressor) run on
    two otherwise-identical engines: swap disabled (victim re-prefills
    on resume) vs ``swap_threshold_blocks=0`` (victim's KV blocks ride
    host RAM and are copied back in). Victim completion latency and the
    swap counters are the crossover evidence behind the threshold
    default."""
    import time as _time

    from deeplearning4j_tpu.serving import GenerationEngine, QosPolicy

    max_new = 24 if on_tpu else 12
    p = np.random.default_rng(5).integers(
        1, gcfg.vocab_size, 8).astype(np.int32)

    # ---- (a) resume-from-watermark vs full replay ---------------------
    with GenerationEngine(gparams, gcfg, slots=2, max_len=64,
                          block_size=8, name="rec-bench") as eng:
        full = eng.generate(p, max_new_tokens=max_new, eos_id=None,
                            timeout=600)           # warm + the oracle
        w = max_new // 2
        # warm the resume path's prefill bucket (prompt + watermark
        # tokens ride one feed) so compile time stays out of the timing
        eng.submit(p, max_new_tokens=max_new, eos_id=None,
                   resume_tokens=np.asarray(full[:w], np.int32),
                   resume_step=w).result(timeout=600)
        t0 = _time.perf_counter()
        replay = eng.generate(p, max_new_tokens=max_new, eos_id=None,
                              timeout=600)
        replay_ms = (_time.perf_counter() - t0) * 1e3
        t0 = _time.perf_counter()
        resumed = eng.submit(p, max_new_tokens=max_new, eos_id=None,
                             resume_tokens=np.asarray(full[:w], np.int32),
                             resume_step=w).result(timeout=600)
        resume_ms = (_time.perf_counter() - t0) * 1e3
        # bitwise: the resumed handle delivers exactly the REMAINING
        # tokens (nothing already delivered is re-decoded)
        assert replay == full and list(resumed) == list(full[w:])

    # ---- (b) preempt-resume: recompute vs swap-to-host ----------------
    qos = QosPolicy(tenants={"fast": {"priority": "interactive"},
                             "slow": {"priority": "batch"}})

    def preempt_run(**swap_kw):
        with GenerationEngine(gparams, gcfg, slots=2, max_len=32,
                              block_size=8, num_blocks=5,
                              allocate="on_demand", qos=qos,
                              queue_capacity=8, name="rec-bench-p",
                              **swap_kw) as eng:
            t0 = _time.perf_counter()
            hv = eng.submit(p, max_new_tokens=20, eos_id=None,
                            tenant="slow")
            ha = eng.submit(np.random.default_rng(6).integers(
                1, gcfg.vocab_size, 4).astype(np.int32),
                max_new_tokens=20, eos_id=None, tenant="fast")
            victim = hv.result(timeout=600)
            victim_ms = (_time.perf_counter() - t0) * 1e3
            ha.result(timeout=600)
            return victim, victim_ms, {
                "preemptions": int(eng.metrics.preemptions_total.value),
                "kv_swapped_blocks": int(
                    eng.metrics.kv_swapped_blocks.value),
                "kv_swap_bytes_out": int(
                    eng.metrics.kv_swap_bytes_out.value),
            }

    v_rec, recompute_ms, rec_stats = preempt_run()
    v_swap, swap_ms, swap_stats = preempt_run(swap_threshold_blocks=0,
                                              swap_capacity_blocks=64)
    assert v_rec == v_swap        # bitwise across both resume paths

    return {
        "stream_replay_ms": round(replay_ms, 3),
        "stream_resume_ms": round(resume_ms, 3),
        "resume_speedup": round(replay_ms / resume_ms, 4)
            if resume_ms else None,
        "resume_watermark": w,
        "preempt_victim_ms_recompute": round(recompute_ms, 3),
        "preempt_victim_ms_swap": round(swap_ms, 3),
        "preempt_stats_recompute": rec_stats,
        "preempt_stats_swap": swap_stats,
    }


def rpc_subleg(on_tpu: bool, gcfg, gparams, slots: int,
               max_len: int) -> dict:
    """RPC data-plane sub-leg (serving/rpc.py — ISSUE 12): (a) per-
    dispatch overhead of the HTTP HostHandle vs the loopback direct
    call (same engine, same rows — the wire's round-trip tax); (b)
    routed TTFT p50 for generation streams fanned over a 3-host HTTP
    fleet (every hop crosses a real socket); (c) hedged vs unhedged
    stream-latency p99 under a seeded 5% ``rpc.dispatch`` latency-spike
    plan — the Tail-at-Scale claim measured: with hedging off a spiked
    dispatch stalls its whole stream for the spike, with hedging on the
    stall monitor opens a backup attempt and the tail collapses."""
    import time as _time

    from deeplearning4j_tpu.serving import (
        ClusterDirectory, ClusterFrontDoor, FaultPlan, GenerationEngine,
        HeartbeatPump, HedgePolicy, HostRpcServer, InferenceEngine,
        LoopbackHost, LoopbackTransport, ModelAdapter, RemoteHost)

    class _Mlp(ModelAdapter):
        def __init__(self):
            super().__init__(model=None)
            self.w = np.linspace(-1, 1, 16, dtype=np.float32).reshape(16, 1)

        def infer(self, x):
            return np.asarray(x) @ self.w

    # ---- (a) loopback vs HTTP dispatch overhead -----------------------
    eng = InferenceEngine(_Mlp(), max_batch_size=8, max_wait_ms=0.0,
                          name="rpc-bench-e")
    local = LoopbackHost(0, engine=eng)
    srv = HostRpcServer(local)
    remote = RemoteHost(0, srv.url)
    x = np.ones((8, 16), np.float32)
    try:
        def p50_dispatch(host, n=80, warm=10):
            for _ in range(warm):
                host.submit_infer(x).result(timeout=60)
            lats = []
            for _ in range(n):
                t0 = _time.perf_counter()
                host.submit_infer(x).result(timeout=60)
                lats.append((_time.perf_counter() - t0) * 1e3)
            return float(np.median(lats))

        loop_p50 = p50_dispatch(local)
        http_p50 = p50_dispatch(remote)
    finally:
        srv.stop()
        local.shutdown()

    # ---- (b) + (c): a 3-host HTTP generation fleet --------------------
    n_streams, max_new = (24, 16) if on_tpu else (30, 4)
    d = ClusterDirectory(heartbeat_timeout_s=30.0)
    servers, locals_, remotes = [], [], []
    for i in range(3):
        g = GenerationEngine(gparams, gcfg, slots=slots, max_len=max_len,
                             queue_capacity=n_streams + slots,
                             name=f"rpc-bench-g{i}")
        lh = LoopbackHost(i, generation=g)
        sv = HostRpcServer(lh)
        rm = RemoteHost(i, sv.url, poll_wait_ms=25.0)
        d.join(rm)
        HeartbeatPump(rm, LoopbackTransport(d)).pump_once()
        servers.append(sv)
        locals_.append(lh)
        remotes.append(rm)
    rng = np.random.default_rng(0)

    def run_streams(fd, n, plan=None):
        """Sequential streams (isolates per-stream latency from slot
        contention); returns (ttfts_ms, latencies_ms)."""
        from contextlib import nullcontext

        ttfts, lats = [], []
        ctx = plan if plan is not None else nullcontext()
        with ctx:
            for _ in range(n):
                first = {"t": None}
                t0 = _time.perf_counter()

                def on_token(_tok, first=first, t0=t0):
                    if first["t"] is None:
                        first["t"] = (_time.perf_counter() - t0) * 1e3

                h = fd.submit_generate(
                    rng.integers(1, gcfg.vocab_size, 12).astype(np.int32),
                    max_new_tokens=max_new, on_token=on_token)
                h.result(timeout=600)
                lats.append((_time.perf_counter() - t0) * 1e3)
                if first["t"] is not None:
                    ttfts.append(first["t"])
        return ttfts, lats

    spike_ms = 400.0

    def spike_plan():
        return FaultPlan(seed=7).delay("rpc.dispatch", spike_ms, rate=0.05)

    try:
        # warm every host's executables out of the measurements
        for i in range(3):
            ClusterFrontDoor(d, name=f"warm{i}").submit_generate(
                rng.integers(1, gcfg.vocab_size, 8).astype(np.int32),
                max_new_tokens=2, host=i).result(timeout=600)

        fd_clean = ClusterFrontDoor(d, name="rpc-clean",
                                    hedge=HedgePolicy(hedge_after_ms=None))
        ttfts, _ = run_streams(fd_clean, n_streams)
        routed = fd_clean.routed_by_host.to_dict()

        fd_unhedged = ClusterFrontDoor(
            d, name="rpc-unhedged", hedge=HedgePolicy(hedge_after_ms=None))
        _, lats_unhedged = run_streams(fd_unhedged, n_streams,
                                       plan=spike_plan())

        fd_hedged = ClusterFrontDoor(
            d, name="rpc-hedged",
            hedge=HedgePolicy(hedge_after_ms=80.0, max_attempts=3,
                              poll_wait_ms=25.0))
        _, lats_hedged = run_streams(fd_hedged, n_streams,
                                     plan=spike_plan())
        hedge_mix = fd_hedged.hedges.to_dict()
    finally:
        for sv in servers:
            sv.stop()
        for lh in locals_:
            lh.shutdown()

    return {
        "loopback_dispatch_p50_ms": round(loop_p50, 3),
        "http_dispatch_p50_ms": round(http_p50, 3),
        "http_overhead_p50_ms": round(http_p50 - loop_p50, 3),
        "routed_ttft_p50_ms_http": round(float(np.median(ttfts)), 3)
            if ttfts else None,
        "gen_routed_by_host": routed,
        "hedge_spike_plan": {"point": "rpc.dispatch", "rate": 0.05,
                             "delay_ms": spike_ms, "seed": 7},
        "stream_p99_ms_unhedged": round(
            float(np.percentile(lats_unhedged, 99)), 3),
        "stream_p99_ms_hedged": round(
            float(np.percentile(lats_hedged, 99)), 3),
        "hedges": hedge_mix,
    }


def disagg_subleg(on_tpu: bool, gcfg, gparams, slots: int,
                  max_len: int) -> dict:
    """Disaggregated serving sub-leg (ISSUE 16 — serving/disagg.py):
    the same fixed 2-host fleet run mixed (both hosts ``host_class=
    "mixed"``, no policy) and disaggregated (1 prefill + 1 decode
    behind :class:`DisaggPolicy`), same prompt schedule. Reports TTFT
    p50 and ITL p99 for both placements, plus the migration-path
    numbers only the disaggregated run has: migrations vs degrade
    fallbacks, KV bytes migrated per stream, and the fleet prefix hit
    rate (wave 2 repeats wave 1's prompts, so the radix-routed decode
    host already holds their cached prefixes)."""
    import time as _time

    from deeplearning4j_tpu.serving import (
        ClusterDirectory, ClusterFrontDoor, DisaggPolicy, GenerationEngine,
        HeartbeatPump, LoopbackHost, LoopbackTransport)

    n_prompts, prompt_len, max_new = 4, 12, 16
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, gcfg.vocab_size, prompt_len).astype(np.int32)
               for _ in range(n_prompts)]

    def run_fleet(disaggregated: bool) -> dict:
        classes = ("prefill", "decode") if disaggregated \
            else ("mixed", "mixed")
        d = ClusterDirectory(heartbeat_timeout_s=5.0)
        engines, hosts, pumps = [], [], []
        for i, cls in enumerate(classes):
            g = GenerationEngine(gparams, gcfg, slots=slots,
                                 max_len=max_len, prefix_cache_blocks=8,
                                 name=f"disagg-{cls}{i}")
            h = LoopbackHost(i, generation=g, host_class=cls)
            d.join(h)
            pumps.append(HeartbeatPump(h, LoopbackTransport(d)))
            engines.append(g)
            hosts.append(h)
        for p in pumps:
            p.pump_once()
        fd = ClusterFrontDoor(
            d, disagg=DisaggPolicy() if disaggregated else None)
        try:
            # warm both hosts' executables out of the measurement
            for i in range(len(hosts)):
                fd.submit_generate(prompts[0], max_new_tokens=2,
                                   host=i).result(timeout=600)
            ttfts, itls = [], []

            def run_wave():
                handles = []
                for toks in prompts:
                    stamps = []
                    t0 = _time.perf_counter()
                    handles.append((stamps, t0, fd.submit_generate(
                        toks, max_new_tokens=max_new,
                        on_token=lambda _t, s=stamps:
                            s.append(_time.perf_counter()))))
                for stamps, t0, h in handles:
                    h.result(timeout=600)
                    if stamps:
                        ttfts.append((stamps[0] - t0) * 1e3)
                    itls.extend((b - a) * 1e3
                                for a, b in zip(stamps, stamps[1:]))

            run_wave()
            # wave 1's retired streams fill the decode-side prefix
            # cache; the next heartbeats advertise it, so wave 2's
            # repeat prompts can radix-route to the host holding them
            deadline = _time.time() + 10
            while (disaggregated and _time.time() < deadline
                   and len(engines[1]._prefix_cache or ()) == 0):
                _time.sleep(0.02)
            for p in pumps:
                p.pump_once()
            run_wave()

            out = {
                "ttft_p50_ms": round(float(np.median(ttfts)), 3),
                "itl_p99_ms": round(float(np.percentile(itls, 99)), 3),
            }
            if disaggregated:
                streams = 2 * n_prompts
                out.update({
                    "migrations": int(
                        fd.metrics.kv_migrations_total.value),
                    "migrate_fallbacks": int(
                        fd.metrics.kv_migrate_fallbacks_total.value),
                    "migrated_bytes_per_stream": round(
                        engines[1].metrics.kv_migrate_bytes_in.value
                        / streams, 1),
                    "prefix_route_hits": int(
                        fd.metrics.prefix_route_hits_total.value),
                    "fleet_prefix_hit_rate": round(
                        fd.metrics.prefix_route_hits_total.value
                        / n_prompts, 4),
                })
            return out
        finally:
            for h in hosts:
                h.shutdown()

    return {
        "fleet": {"hosts": 2, "slots_per_host": slots,
                  "prompts": 2 * n_prompts, "max_new_tokens": max_new},
        "mixed": run_fleet(False),
        "disaggregated": run_fleet(True),
    }


def soak_leg(on_tpu: bool) -> dict:
    """Fleet chaos soak (ISSUE 18): three real HTTP hosts over the RPC
    plane take the seeded trace mix (chat/rag/batch over an on/off
    arrival process) while the seeded episode schedule fires kill,
    drain, preemption-storm, swap-pressure and rpc-fault episodes.

    The headline numbers: sustained tokens/sec over the whole soak,
    p99 latency DURING chaos-episode windows vs BETWEEN them (the tail
    price of chaos), worst recovery-time-to-SLO after a kill/drain, and
    the ledger verdict — True means every block, swap entry, op and
    thread returned to its post-warmup baseline. Seeded end to end:
    same seed, same episodes, same trace, so a drift here is a
    robustness regression, not noise."""
    from tools.soak import run_soak

    seed = 3
    duration_s = 16.0 if on_tpu else 14.0
    report = run_soak(seed=seed, duration_s=duration_s, n_hosts=3,
                      rate_rps=3.0, mean_gap_s=3.0)
    d = report.to_dict()
    load = d["load"]
    rec = d["recovery_to_slo_s"]
    return {
        "seed": seed,
        "duration_s": duration_s,
        "episodes_fired": d["episodes_fired"],
        "episode_kinds": sorted({r.episode.kind
                                 for r in report.episodes}),
        "requests": load["requests"],
        "ok": load["ok"],
        "stuck_streams": load["stuck_streams"],
        "tokens_per_sec": load["tokens_per_sec"],
        "watermark_clean": load["watermark_clean"],
        "latency_p99_during_episodes_ms":
            round(load["latency_p99_during_episodes_ms"], 3)
            if load["latency_p99_during_episodes_ms"] is not None
            else None,
        "latency_p99_between_episodes_ms":
            round(load["latency_p99_between_episodes_ms"], 3)
            if load["latency_p99_between_episodes_ms"] is not None
            else None,
        "recovery_to_slo_s": rec,
        "max_recovery_to_slo_s": d["max_recovery_to_slo_s"],
        "ledger_clean": d["ledger_clean"],
        "ledger_violations": d["ledger_violations"],
    }


if __name__ == "__main__":
    main()
