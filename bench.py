"""Headline benchmark: BERT-base masked-LM training throughput on one chip.

Mirrors BASELINE.json's metric ("SameDiff BERT-base tokens/sec/chip"): the
reference runs this workload through the SameDiff op-by-op JVM interpreter;
here it is one fused XLA executable (fwd+bwd+AdamW, bf16 compute, no remat —
activations fit HBM at bench shapes and recompute cost ~15% throughput).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured MFU / 0.35 (the north-star gate from
BASELINE.json) since the reference publishes no in-tree numbers
(SURVEY.md §6, BASELINE "published": {}).
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _peak_flops(device) -> float:
    from deeplearning4j_tpu.profiler.profiler import peak_flops
    return peak_flops(device)


def main():
    from deeplearning4j_tpu.models import (
        TransformerConfig, init_params, make_train_step)

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        # BERT-base 12L/768H/12 heads/512 seq. remat off: activations fit a
        # single chip's HBM at B=48 and recompute costs ~15% throughput
        # (measured: 117k tok/s no-remat vs 100k dots-remat vs 96k full).
        # attention_impl='flash' routes to the packed whole-head VMEM Pallas
        # kernel (fwd+bwd on-chip, no (T,T) HBM traffic, no head
        # transposes) — the round-4 lever that broke the round-2/3 HBM
        # plateau (tools/profile_flagship.py: the XLA attention score path
        # was 67 ms of the 182 ms step). softmax stays fp32: the kernel's
        # bf16 p_dtype saves VPU time standalone but the full step hides it
        # under DMA (measured parity), so exactness is free. B=96: with the
        # kernel, throughput rises past the old B=48 plateau (B sweep:
        # 48 -> 163k, 96 -> 172k, 128 -> 160k).
        cfg = TransformerConfig(remat=False, attention_impl="flash")
        B, T, steps, warmup = 96, 512, 10, 3
    else:                                   # CPU smoke fallback (driver runs TPU)
        cfg = TransformerConfig(vocab_size=1024, hidden=128, layers=2, heads=4,
                                mlp_dim=512, max_seq=128, dtype=jnp.float32,
                                remat=False)
        B, T, steps, warmup = 8, 128, 3, 1

    key = jax.random.PRNGKey(0)
    params = init_params(key, cfg)
    init_state, step = make_train_step(cfg, learning_rate=1e-4)
    opt_state = init_state(params)

    rng = np.random.default_rng(0)
    def make_batch():
        return {
            "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
            "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
            "weights": jnp.ones((B, T), jnp.float32),
        }

    batch = make_batch()
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch)
    # NB: under the axon tunnel block_until_ready is a no-op; a host transfer
    # is the only reliable synchronization point.
    float(loss)

    # median of 3 timing windows: the axon tunnel adds sporadic per-window
    # latency (~±3% observed); the median is the honest steady-state number
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
        float(loss)
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[1]

    tokens_per_sec = B * T * steps / dt

    # MFU on the repo-wide single basis (profiler.MFU_BASIS): analytic model
    # flops, no remat recompute at bench config. XLA-counted flops for the
    # same step live in the committed profile artifact as mfu_xla
    # (tools/profile_flagship.py).
    from deeplearning4j_tpu.profiler.profiler import (
        MFU_BASIS, mfu as _mfu, non_embedding_params,
        transformer_flops_per_token)
    flops_per_token = transformer_flops_per_token(
        non_embedding_params(params, cfg), cfg.layers, cfg.hidden, T)
    peak = _peak_flops(jax.devices()[0]) if on_tpu else 1e12
    mfu = _mfu(tokens_per_sec, flops_per_token, peak)

    print(json.dumps({
        "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec",
        "mfu": round(mfu, 4),
        "mfu_basis": MFU_BASIS,
        "vs_baseline": round(mfu / 0.35, 4),
        "vs_baseline_basis": "mfu / 0.35 north-star gate (BASELINE.json)",
    }))


if __name__ == "__main__":
    main()
