"""Build + freeze a BERT-style encoder as a TF GraphDef.

Reference workload generator for BASELINE config #4 ("SameDiff BERT-base
TF-import fine-tune"): the reference imports a frozen TF BERT through
nd4j/samediff-import-tensorflow (SURVEY §3.3). The environment has no network,
so the graph is constructed locally (randomly initialized weights) with the
same architecture/op mix a frozen BERT checkpoint produces: Gather embeddings,
layernorm via moments, multi-head attention as reshape/transpose/BatchMatMulV2,
erf-GELU, dense MatMul+BiasAdd.

Returns ~1.4k nodes at BERT-base size — the import-at-scale exercise VERDICT
r1 called for.
"""
from __future__ import annotations

import numpy as np


def build_frozen_bert(L=12, H=768, A=12, V=30522, T=128, intermediate=3072,
                      seed=0, masked=False):
    """Returns (graph_def, input_name, output_name, concrete_fn).

    Output: final-layer hidden states (B, T, H) of a token-id input (B, T).
    ``masked=True`` adds the standard BERT additive padding mask (a second
    (B, T) float input; scores get ``(1 - m) * -1e4`` after scaling) —
    input names become a 2-tuple (ids, mask).
    """
    import tensorflow as tf

    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.02):
        return tf.constant(rng.normal(0, scale, shape).astype(np.float32))

    tok_emb = w(V, H)
    pos_emb = w(T, H)
    ln_g = [tf.constant(np.ones((H,), np.float32)) for _ in range(2 * L + 1)]
    ln_b = [tf.constant(np.zeros((H,), np.float32)) for _ in range(2 * L + 1)]
    qkv_w = [w(H, 3 * H) for _ in range(L)]
    qkv_b = [tf.constant(np.zeros((3 * H,), np.float32)) for _ in range(L)]
    proj_w = [w(H, H) for _ in range(L)]
    proj_b = [tf.constant(np.zeros((H,), np.float32)) for _ in range(L)]
    fc1_w = [w(H, intermediate) for _ in range(L)]
    fc1_b = [tf.constant(np.zeros((intermediate,), np.float32)) for _ in range(L)]
    fc2_w = [w(intermediate, H) for _ in range(L)]
    fc2_b = [tf.constant(np.zeros((H,), np.float32)) for _ in range(L)]
    D = H // A

    def layer_norm(x, g, b, eps=1e-12):
        mean, var = tf.nn.moments(x, axes=[-1], keepdims=True)
        return (x - mean) * tf.math.rsqrt(var + eps) * g + b

    def gelu(x):
        return 0.5 * x * (1.0 + tf.math.erf(x / np.sqrt(2.0).astype(np.float32)))

    def encoder(ids, mask=None):
        B = tf.shape(ids)[0]
        if mask is not None:
            # (B, T) -> additive (B, 1, 1, T), BERT convention
            adder = (1.0 - mask[:, tf.newaxis, tf.newaxis, :]) \
                * tf.constant(-1e4, tf.float32)
        x = tf.gather(tok_emb, ids) + pos_emb[tf.newaxis]
        x = layer_norm(x, ln_g[2 * L], ln_b[2 * L])
        for i in range(L):
            h = layer_norm(x, ln_g[2 * i], ln_b[2 * i])
            qkv = tf.matmul(h, qkv_w[i]) + qkv_b[i]
            q, k, v = tf.split(qkv, 3, axis=-1)

            def heads(t):
                t = tf.reshape(t, (B, T, A, D))
                return tf.transpose(t, (0, 2, 1, 3))

            s = tf.matmul(heads(q), heads(k), transpose_b=True)
            s = s * tf.constant(1.0 / np.sqrt(D), tf.float32)
            if mask is not None:
                s = s + adder
            p = tf.nn.softmax(s, axis=-1)
            o = tf.matmul(p, heads(v))
            o = tf.reshape(tf.transpose(o, (0, 2, 1, 3)), (B, T, H))
            x = x + tf.matmul(o, proj_w[i]) + proj_b[i]
            h = layer_norm(x, ln_g[2 * i + 1], ln_b[2 * i + 1])
            h = gelu(tf.matmul(h, fc1_w[i]) + fc1_b[i])
            x = x + tf.matmul(h, fc2_w[i]) + fc2_b[i]
        return x

    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    if masked:
        cf = tf.function(encoder).get_concrete_function(
            tf.TensorSpec((None, T), tf.int32),
            tf.TensorSpec((None, T), tf.float32))
    else:
        cf = tf.function(encoder).get_concrete_function(
            tf.TensorSpec((None, T), tf.int32))
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    out_name = frozen.outputs[0].name.split(":")[0]
    if masked:
        in_names = tuple(t.name.split(":")[0] for t in frozen.inputs)
        return gd, in_names, out_name, frozen
    in_name = frozen.inputs[0].name.split(":")[0]
    return gd, in_name, out_name, frozen
