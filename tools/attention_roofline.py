"""Round-5 attention-kernel roofline: measure WHY the packed whole-head VMEM
kernel runs at ~50 TFLOP/s at BERT-base shapes (D=64) and what the ceiling is.

Experiments (all standalone kernel timings at bench shapes B=96, T=512,
hidden=768, fwd+bwd unless noted):

1. head-width sweep — the SAME kernel at heads=12/D=64 (bench), heads=6/D=128,
   heads=4/D=192, heads=24/D=32. Total attention matmul FLOPs are identical
   (sum_h T^2*D = T^2*hidden); only the MXU contraction depth of the QK^T and
   dp=do@v^T dots changes. The D trend isolates the systolic-array fill cost
   (K=64 of 128 rows -> ~50% issue ceiling on 2 of the 6 matmuls) from
   everything else.
2. matmul-only variant — softmax replaced by a flat scale (same dots, same
   dataflow, no exp/max/sum): isolates MXU+DMA time from VPU softmax time.
3. batched-dot variant — per-head Python loop replaced by one
   (H,T,D)x(H,T,D)->(H,T,T) batched dot_general with vectorized softmax
   (the (H,T,T) scores block lives whole in VMEM, 12.6 MB fp32): tests
   whether per-head loop serialization (MXU idle during each head's VPU
   softmax) is the gap.

A note on the round-5 verdict's "two-head packing" suggestion: folding head
pairs into one D=128 contraction is mathematically invalid for QK^T —
[q1|q2] @ [k1|k2]^T = q1k1^T + q2k2^T sums the two heads' score matrices
(softmax then mixes heads irrecoverably). The head-width sweep above is the
honest way to measure what D=128 would buy.

Usage: python tools/attention_roofline.py  (runs on the real TPU; prints a
JSON report — commit the numbers into BASELINE.md).
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops.pallas_kernels import (
    _tpu_params, mha_attention_packed)

B, T, HIDDEN = 96, 512, 768
STEPS, WARMUP = 20, 3


CHAIN = 12  # applications chained inside ONE jit executable: the axon
#             tunnel's per-dispatch latency (~5 ms observed on this harness's
#             first cut) otherwise swamps a ~1-3 ms kernel


def _sync(x):
    # block_until_ready is a no-op under the axon tunnel; host transfer syncs
    return float(jnp.sum(x[0]) if isinstance(x, tuple) else jnp.sum(x))


def _time(fn, *args):
    """Median per-APPLICATION seconds: fn must chain CHAIN applications."""
    for _ in range(WARMUP):
        out = fn(*args)
    _sync(out)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        _sync(out)
        dts.append((time.perf_counter() - t0) / (STEPS * CHAIN))
    return sorted(dts)[1]


def _attention_flops(fwd_bwd: bool) -> float:
    # per head: QK^T (2*T*T*D) + PV (2*T*T*D); summed over heads: 4*T^2*HIDDEN
    # bwd adds dv, dp, dq, dk = 4 more T^2-by-D dots -> 2x fwd
    f = 4 * T * T * HIDDEN * B
    return f * 3 if fwd_bwd else f


# ---------------------------------------------------------------- variants


def _matmul_only_kernel(q_ref, k_ref, v_ref, o_ref, *, heads, scale):
    """The packed kernel's dot dataflow with softmax replaced by a flat
    scale — same matmuls, no VPU exp/max/sum."""
    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    t, hd = q.shape
    d = hd // heads
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
    for h in range(heads):
        sl = slice(h * d, (h + 1) * d)
        s = jax.lax.dot_general(qs[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        p = (s * (1.0 / t)).astype(q.dtype)   # stand-in normalization
        o = jax.lax.dot_general(p, v[:, sl], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, :, sl] = o.astype(o_ref.dtype)


def matmul_only(q, k, v, heads):
    t, hd = q.shape[1], q.shape[2]
    d = hd // heads
    blk = pl.BlockSpec((1, t, hd), lambda i: (i, 0, 0))
    # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
    return pl.pallas_call(
        functools.partial(_matmul_only_kernel, heads=heads,
                          scale=1.0 / (d ** 0.5)),
        grid=(q.shape[0],),
        in_specs=[blk, blk, blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_tpu_params(),
    )(q, k, v)


def _interleaved_kernel(q_ref, k_ref, v_ref, o_ref, *, heads, scale):
    """Software-pipelined heads loop: head h+1's QK^T dot is issued BEFORE
    head h's softmax/PV, giving the scheduler a data-independent MXU op to
    overlap with the VPU softmax. Motivation: measured fwd time is exactly
    matmul-only + softmax-only (2.25 = 1.48 + 0.75 ms) — zero overlap in
    the naive loop order. NB: after this variant measured -23% (2.06 ->
    1.58 ms), the pipelining was SHIPPED into the production
    _mha_packed_fwd_kernel/_mha_packed_bwd_kernel and the streamed flash
    kernels, so on current code the packed_fwd and interleaved_fwd rows
    measure the same structure (kept for the historical A/B)."""
    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    t, hd = q.shape
    d = hd // heads
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)

    def qk(h):
        sl = slice(h * d, (h + 1) * d)
        return jax.lax.dot_general(qs[:, sl], k[:, sl],
                                   (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    s_next = qk(0)
    for h in range(heads):
        s = s_next
        if h + 1 < heads:
            s_next = qk(h + 1)   # independent MXU work to hide softmax under
        sl = slice(h * d, (h + 1) * d)
        m = s.max(-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general((p / l).astype(q.dtype), v[:, sl],
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, :, sl] = o.astype(o_ref.dtype)


def interleaved(q, k, v, heads):
    t, hd = q.shape[1], q.shape[2]
    d = hd // heads
    blk = pl.BlockSpec((1, t, hd), lambda i: (i, 0, 0))
    # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
    return pl.pallas_call(
        functools.partial(_interleaved_kernel, heads=heads,
                          scale=1.0 / (d ** 0.5)),
        grid=(q.shape[0],),
        in_specs=[blk, blk, blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_tpu_params(),
    )(q, k, v)


def _batched_dot_kernel(q_ref, k_ref, v_ref, o_ref, *, heads, scale):
    """All heads in ONE batched dot_general; softmax vectorized over (H,T,T)."""
    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    t, hd = q.shape
    d = hd // heads
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qh = qs.reshape(t, heads, d).transpose(1, 0, 2)   # (H, T, D) in VMEM
    kh = k.reshape(t, heads, d).transpose(1, 0, 2)
    vh = v.reshape(t, heads, d).transpose(1, 0, 2)
    s = jax.lax.dot_general(qh, kh, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)  # (H, T, T)
    m = s.max(-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general((p / l).astype(q.dtype), vh,
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)  # (H, T, D)
    o_ref[0] = o.transpose(1, 0, 2).reshape(t, hd).astype(o_ref.dtype)


def batched_dot(q, k, v, heads):
    t, hd = q.shape[1], q.shape[2]
    d = hd // heads
    blk = pl.BlockSpec((1, t, hd), lambda i: (i, 0, 0))
    # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
    return pl.pallas_call(
        functools.partial(_batched_dot_kernel, heads=heads,
                          scale=1.0 / (d ** 0.5)),
        grid=(q.shape[0],),
        in_specs=[blk, blk, blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_tpu_params(),
    )(q, k, v)


def main():
    assert jax.default_backend() != "cpu", "roofline runs on the real TPU"
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, HIDDEN)) * 0.1,
                           jnp.bfloat16) for _ in range(3))
    g = jnp.asarray(rng.normal(size=(B, T, HIDDEN)) * 0.1, jnp.bfloat16)
    report = {"device": str(jax.devices()[0]), "B": B, "T": T,
              "hidden": HIDDEN, "results": []}

    def add(name, sec, fwd_bwd, extra=None):
        tf = _attention_flops(fwd_bwd) / sec / 1e12
        row = {"variant": name, "ms_per_application": round(sec * 1e3, 3),
               "achieved_tflops": round(tf, 2), **(extra or {})}
        report["results"].append(row)
        print(f"  {name}: {sec*1e3:.3f} ms  ->  {tf:.1f} TF/s", flush=True)

    def chain_fwd(apply):
        """CHAIN serially-dependent applications in one executable (the
        output feeds the next q, like stacked layers)."""
        def fn(q, k, v):
            def body(i, acc):
                return apply(acc, k, v)
            return jax.lax.fori_loop(0, CHAIN, body, q)
        # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
        return jax.jit(fn)

    def chain_fwdbwd(apply):
        def loss(q, k, v):
            def body(i, acc):
                return apply(acc, k, v)
            out = jax.lax.fori_loop(0, CHAIN, body, q)
            return jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32))
        # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    # 1. head-width sweep, fwd and fwd+bwd (identical total matmul flops)
    for heads in (24, 12, 6, 4):
        d = HIDDEN // heads
        apply = lambda q, k, v, h=heads: mha_attention_packed(
            q, k, v, h, False, None, False, jnp.float32)
        add(f"packed_fwd_heads{heads}_D{d}", _time(chain_fwd(apply), q, k, v),
            False)
        add(f"packed_fwdbwd_heads{heads}_D{d}",
            _time(chain_fwdbwd(apply), q, k, v), True)

    # p_dtype=bf16 at the bench head count (VPU halving check)
    apply = lambda q, k, v: mha_attention_packed(
        q, k, v, 12, False, None, False, jnp.bfloat16)
    add("packed_fwdbwd_heads12_D64_pbf16",
        _time(chain_fwdbwd(apply), q, k, v), True)

    # 2. matmul-only (VPU softmax removed), fwd
    add("matmul_only_fwd_heads12_D64",
        _time(chain_fwd(lambda q, k, v: matmul_only(q, k, v, 12)), q, k, v),
        False)
    add("matmul_only_fwd_heads6_D128",
        _time(chain_fwd(lambda q, k, v: matmul_only(q, k, v, 6)), q, k, v),
        False)

    # 2b. software-pipelined heads loop (MXU/VPU overlap test)
    add("interleaved_fwd_heads12_D64",
        _time(chain_fwd(lambda q, k, v: interleaved(q, k, v, 12)), q, k, v),
        False)

    # 3. batched-dot variant (loop serialization test). NB first cut:
    # Mosaic rejects the (H,T,T) batched dot_general with an internal
    # tpu_compile_helper error — kept behind try for the record.
    try:
        add("batched_dot_fwd_heads12_D64",
            _time(chain_fwd(lambda q, k, v: batched_dot(q, k, v, 12)),
                  q, k, v), False)
    except Exception as e:
        report["results"].append({"variant": "batched_dot_fwd_heads12_D64",
                                  "error": repr(e)[:300]})
        print(f"  batched_dot failed: {repr(e)[:200]}", flush=True)

    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
