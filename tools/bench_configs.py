"""Measure the BASELINE.json configs #1-3 on the real TPU and print one JSON
line per config (ref: BASELINE.md "record rebuild numbers alongside").

Configs (#4 lives in tools/bench_tf_import.py, #5 is the multi-chip dryrun):
  1. LeNet-MNIST MultiLayerNetwork       -> images/sec
  2. ResNet-50 ComputationGraph (zoo)    -> images/sec
  3. GravesLSTM char-RNN                 -> tokens/sec

Run: ``python tools/bench_configs.py [--dtype HALF]``. fp32 is the
reference-faithful default (the package pins exact-fp32 GEMMs); HALF shows
the bf16 headroom the reference never had.
"""
import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


_FUSE_OVERRIDE = None  # set by --fuseSteps for the sweep


def _timed_fit(net, ds, steps=16, warmup=None):
    """Seconds per training step, driving fit(iterator) the way real training
    does — which engages the de-dispatched multi-step path (fuseSteps steps
    per XLA executable; BASELINE.md round-4 config tables). ``steps`` should be a multiple
    of net.fuseSteps so the whole run is fused. Synchronization is a host
    transfer of the score (block_until_ready is a no-op under axon)."""
    from deeplearning4j_tpu.data import ListDataSetIterator
    if _FUSE_OVERRIDE is not None:
        net.fuseSteps = _FUSE_OVERRIDE
    k = max(getattr(net, "fuseSteps", 8), 1)
    steps = max(steps, 2 * k)  # always time >= two full fused chunks
    warm = ListDataSetIterator([ds] * (warmup or 2 * k))
    net.fit(warm)                       # compiles multi + leftover step paths
    float(net.score())
    it = ListDataSetIterator([ds] * steps)
    t0 = time.perf_counter()
    net.fit(it)
    float(net.score())
    return (time.perf_counter() - t0) / steps


def bench_lenet(dtype, B=256):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3))
            .dataType(dtype).list()
            .layer(ConvolutionLayer(nOut=20, kernelSize=(5, 5), activation="RELU"))
            .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(nOut=50, kernelSize=(5, 5), activation="RELU"))
            .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(nOut=500, activation="RELU"))
            .layer(OutputLayer(nOut=10, lossFunction="MCXENT"))
            .setInputType(InputType.convolutionalFlat(28, 28, 1)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.random((B, 784), np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, B)])
    dt = _timed_fit(net, ds, steps=32)
    return {"config": "lenet_mnist_mln", "metric": "images_per_sec",
            "value": round(B / dt, 1), "batch": B, "dtype": dtype}


def bench_resnet50(dtype, B=32):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.zoo import ResNet50
    net = ResNet50(numClasses=1000, inputShape=(3, 224, 224)).init()
    if dtype == "HALF":  # zoo builder has no dtype knob; rebuild conf
        net.conf.dataType = "HALF"
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        net = ComputationGraph(net.conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.random((B, 3, 224, 224), np.float32),
                 np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, B)])
    dt = _timed_fit(net, ds, steps=16)
    return {"config": "resnet50_cg", "metric": "images_per_sec",
            "value": round(B / dt, 1), "batch": B, "dtype": dtype}


def bench_graves_lstm(dtype, B=64, T=128, vocab=80, hidden=512):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-3))
            .dataType(dtype).list()
            .layer(GravesLSTM(nOut=hidden, activation="TANH"))
            .layer(GravesLSTM(nOut=hidden, activation="TANH"))
            .layer(RnnOutputLayer(nOut=vocab, lossFunction="MCXENT"))
            .setInputType(InputType.recurrent(vocab, T)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (B, T))]
    y = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (B, T))]
    ds = DataSet(x, y)
    dt = _timed_fit(net, ds, steps=16)
    return {"config": "graves_lstm_char_rnn", "metric": "tokens_per_sec",
            "value": round(B * T / dt, 1), "batch": B, "seq": T, "dtype": dtype}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="FLOAT", choices=["FLOAT", "HALF"])
    ap.add_argument("--only", default=None,
                    choices=[None, "lenet", "resnet", "lstm"])
    ap.add_argument("--fuseSteps", type=int, default=None,
                    help="override the nets' fuseSteps (sweep tooling)")
    args = ap.parse_args()
    global _FUSE_OVERRIDE
    if args.fuseSteps is not None:
        _FUSE_OVERRIDE = args.fuseSteps
    else:
        import jax
        if jax.default_backend() not in ("cpu",):
            # measured sweep (BASELINE.md round 4): 32 beats the library
            # default 8 on every config (ResNet 1030 -> 1197 img/s, LSTM
            # 378k -> 1346k tok/s) — the tunnel's per-dispatch stall is the
            # bottleneck at these step sizes
            _FUSE_OVERRIDE = 32
    benches = {"lenet": bench_lenet, "resnet": bench_resnet50,
               "lstm": bench_graves_lstm}
    for name, fn in benches.items():
        if args.only and name != args.only:
            continue
        print(json.dumps(fn(args.dtype)), flush=True)


if __name__ == "__main__":
    main()
