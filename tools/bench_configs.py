"""Measure the BASELINE.json configs #1-3 on the real TPU and print one JSON
line per config (ref: BASELINE.md "record rebuild numbers alongside").

Configs (#4 lives in tools/bench_tf_import.py, #5 is the multi-chip dryrun):
  1. LeNet-MNIST MultiLayerNetwork       -> images/sec
  2. ResNet-50 ComputationGraph (zoo)    -> images/sec
  3. GravesLSTM char-RNN                 -> tokens/sec

Run: ``python tools/bench_configs.py [--dtype HALF]``. fp32 is the
reference-faithful default (the package pins exact-fp32 GEMMs); HALF shows
the bf16 headroom the reference never had.
"""
import argparse
import json
import math
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


_FUSE_OVERRIDE = None  # set by --fuseSteps for the sweep
_MIN_WINDOW_S = 2.0    # round-5 verdict #4: every timing window must hold
#                        >= ~2 s of device work, so a multi-hundred-ms axon
#                        tunnel stall is a <15% perturbation of ONE window
#                        (not 30,000% of a sub-ms step), and the median
#                        across windows rejects it entirely
_FORENSICS: list = []  # timestamped per-window log (stall evidence)


def _timed_fit(net, ds, steps=16, warmup=None, windows=3, tag=""):
    """Median seconds/step over >= ``windows`` timing windows, each sized to
    at least _MIN_WINDOW_S of work (calibrated), driving fit(iterator) the
    way real training does — the de-dispatched multi-step path (fuseSteps
    steps per XLA executable). Every window is logged with absolute
    timestamps into _FORENSICS; windows whose spread exceeds ±10% trigger up
    to 3 extra windows (tunnel stalls are exogenous multi-hundred-ms gaps —
    the log shows them; the median excludes them). Synchronization is a host
    transfer of the score (block_until_ready is a no-op under axon)."""
    from deeplearning4j_tpu.data import ListDataSetIterator
    if _FUSE_OVERRIDE is not None:
        net.fuseSteps = _FUSE_OVERRIDE
    k = max(getattr(net, "fuseSteps", 8), 1)
    warm = ListDataSetIterator([ds] * (warmup or 2 * k))
    net.fit(warm)                       # compiles multi + leftover step paths
    float(net.score())
    # calibration window sizes the measurement windows to >= _MIN_WINDOW_S
    cal = 2 * k
    t0 = time.perf_counter()
    net.fit(ListDataSetIterator([ds] * cal))
    float(net.score())
    est = (time.perf_counter() - t0) / cal
    steps = max(steps, 2 * k,
                int(math.ceil(_MIN_WINDOW_S / max(est, 1e-9) / k)) * k)
    per = []
    wins = []
    total = 0
    while True:
        total += 1
        w0 = time.time()
        p0 = time.perf_counter()
        net.fit(ListDataSetIterator([ds] * steps))
        float(net.score())
        p1 = time.perf_counter()
        wall = p1 - p0
        row = {"tag": tag, "window": total - 1, "unix_start": round(w0, 3),
               "wall_s": round(wall, 4), "steps": steps,
               "sec_per_step": round(wall / steps, 6)}
        # calibration can itself hit a stall and oversize est -> undersized
        # measurement windows; re-grow whenever a window lands short and
        # keep it out of the median (logged for the forensics regardless)
        if wall < 0.8 * _MIN_WINDOW_S and total <= windows + 3:
            row["undersized"] = True
            wins.append(row)
            steps = max(steps + k, int(
                math.ceil(_MIN_WINDOW_S / max(wall / steps, 1e-9) / k)) * k)
            continue
        wins.append(row)
        per.append(wall / steps)
        # spread over the most recent `windows` measurements: a single early
        # stalled window must not make the convergence check permanently
        # unsatisfiable (max-over-all-history never decreases)
        recent = per[-windows:]
        spread = (max(recent) - min(recent)) / np.median(recent)
        if len(per) >= windows and (spread <= 0.10 or total >= windows + 3):
            break
    _FORENSICS.extend(wins)
    return float(np.median(per)), wins


def _row(config, metric, value, extra, wins):
    """Result row + the run's window forensics (spread, steps/window)."""
    secs = [w["sec_per_step"] for w in wins if not w.get("undersized")]
    spread = (max(secs) - min(secs)) / float(np.median(secs))
    return {"config": config, "metric": metric, "value": round(value, 1),
            **extra, "steps_per_window": wins[-1]["steps"],
            "windows": len(secs), "window_spread": round(spread, 4)}


def bench_lenet(dtype, B=256):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3))
            .dataType(dtype).list()
            .layer(ConvolutionLayer(nOut=20, kernelSize=(5, 5), activation="RELU"))
            .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(nOut=50, kernelSize=(5, 5), activation="RELU"))
            .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(nOut=500, activation="RELU"))
            .layer(OutputLayer(nOut=10, lossFunction="MCXENT"))
            .setInputType(InputType.convolutionalFlat(28, 28, 1)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.random((B, 784), np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, B)])
    dt, wins = _timed_fit(net, ds, steps=32, tag="lenet")
    return _row("lenet_mnist_mln", "images_per_sec", B / dt,
                {"batch": B, "dtype": dtype}, wins)


def bench_resnet50(dtype, B=32):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.zoo import ResNet50
    net = ResNet50(numClasses=1000, inputShape=(3, 224, 224)).init()
    if dtype == "HALF":  # zoo builder has no dtype knob; rebuild conf
        net.conf.dataType = "HALF"
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        net = ComputationGraph(net.conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.random((B, 3, 224, 224), np.float32),
                 np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, B)])
    dt, wins = _timed_fit(net, ds, steps=16, tag="resnet")
    return _row("resnet50_cg", "images_per_sec", B / dt,
                {"batch": B, "dtype": dtype}, wins)


def bench_graves_lstm(dtype, B=64, T=128, vocab=80, hidden=512):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-3))
            .dataType(dtype).list()
            .layer(GravesLSTM(nOut=hidden, activation="TANH"))
            .layer(GravesLSTM(nOut=hidden, activation="TANH"))
            .layer(RnnOutputLayer(nOut=vocab, lossFunction="MCXENT"))
            .setInputType(InputType.recurrent(vocab, T)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (B, T))]
    y = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (B, T))]
    ds = DataSet(x, y)
    dt, wins = _timed_fit(net, ds, steps=16, tag="lstm")
    return _row("graves_lstm_char_rnn", "tokens_per_sec", B * T / dt,
                {"batch": B, "seq": T, "dtype": dtype}, wins)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="FLOAT", choices=["FLOAT", "HALF"])
    ap.add_argument("--only", default=None,
                    choices=[None, "lenet", "resnet", "lstm"])
    ap.add_argument("--fuseSteps", type=int, default=None,
                    help="override the nets' fuseSteps (sweep tooling)")
    ap.add_argument("--forensics", default=None,
                    help="write the timestamped per-window log (stall "
                         "evidence, round-5 verdict #4) to this JSON file")
    args = ap.parse_args()
    global _FUSE_OVERRIDE
    if args.fuseSteps is not None:
        _FUSE_OVERRIDE = args.fuseSteps
    else:
        import jax
        if jax.default_backend() not in ("cpu",):
            # measured sweep (BASELINE.md round 4): 32 beats the library
            # default 8 on every config (ResNet 1030 -> 1197 img/s, LSTM
            # 378k -> 1346k tok/s) — the tunnel's per-dispatch stall is the
            # bottleneck at these step sizes
            _FUSE_OVERRIDE = 32
    benches = {"lenet": bench_lenet, "resnet": bench_resnet50,
               "lstm": bench_graves_lstm}
    for name, fn in benches.items():
        if args.only and name != args.only:
            continue
        print(json.dumps(fn(args.dtype)), flush=True)
    if args.forensics:
        with open(args.forensics, "w") as f:
            json.dump({"min_window_s": _MIN_WINDOW_S,
                       "fuse_override": _FUSE_OVERRIDE,
                       "windows": _FORENSICS}, f, indent=1)


if __name__ == "__main__":
    main()
