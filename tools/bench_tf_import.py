"""Bench variant for BASELINE config #4 THROUGH the import path: a frozen
BERT-base GraphDef is imported into SameDiff and fine-tuned under whole-graph
jit (vs bench.py which trains the hand-written flagship transformer).

Run manually: python tools/bench_tf_import.py
Prints one JSON line in the same format as bench.py. ``vs_baseline`` is MFU
against the 35% north-star gate, as in bench.py.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    import argparse
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train import Adam
    from deeplearning4j_tpu.modelimport.tensorflow import TensorflowFrameworkImporter
    from tools.tf_bert import build_frozen_bert
    from bench import _peak_flops

    ap = argparse.ArgumentParser()
    # HALF is the default: the import-time mixed-precision rewrite
    # (TrainingConfig.computeDtype) is the whole-graph-compile payoff this
    # config exists to show (fp32 numbers stay reproducible via --dtype FLOAT)
    ap.add_argument("--dtype", default="HALF", choices=["FLOAT", "HALF"])
    # representative configuration (round-5 verdict #2): a score listener
    # attached the way reference users run sd.fit — must stay within ~5%
    # of the listener-free number now that SameDiff.fit fuses through
    # listeners via requiresModelAtIteration chunking
    ap.add_argument("--listener", action="store_true",
                    help="attach ScoreIterationListener(10) during timing")
    ap.add_argument("--fuse-attention", action="store_true",
                    help="run sd.fuseAttention() before training (collapse "
                    "imported matmul/scale/softmax/matmul chains onto the "
                    "Pallas-backed fused attention op)")
    args = ap.parse_args()

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        L, H, A, V, T, inter = 12, 768, 12, 30522, 128, 3072
        # steps/warmup sized to the fused fit path: warmup covers one full
        # fuseSteps chunk PLUS leftovers so both the multi-step scan and the
        # single-step executable compile before the timing window.
        # fuseSteps=32 from the measured sweep (BASELINE.md round 4:
        # 8 -> 58k, 16 -> 119k, 32 -> 146k tok/s — each tunnel dispatch
        # costs ~300 ms at these small steps, so deeper chunks win)
        B, steps, warmup = 32, 64, 34
    else:
        L, H, A, V, T, inter = 2, 64, 4, 256, 16, 128
        B, steps, warmup = 4, 3, 1

    gd, in_name, out_name, _ = build_frozen_bert(L=L, H=H, A=A, V=V, T=T,
                                                 intermediate=inter)
    sd = TensorflowFrameworkImporter.runImport(gd)
    sd.convertAllConstantsToVariables()
    if on_tpu:
        sd.fuseSteps = 32  # measured sweep, see comment above
    if args.fuse_attention:
        nf = sd.fuseAttention()
        print(f"# fuseAttention: {nf} sites", file=sys.stderr)
    n_param = sum(int(np.prod(v.shape)) for v in sd.variables()
                  if v.varType == "VARIABLE" and v.shape)

    # MLM head over the imported encoder output
    hidden = sd.getVariable(out_name)
    lm_w = sd.var("lm_head", (H, V), weightInit="XAVIER")
    logits = sd.linalg.matmul(hidden, lm_w)
    targets = sd.placeHolder("targets", shape=(B, T), dtype=jnp.int32)
    loss = sd.loss.sparseMcxent(targets, logits)
    sd.setLossVariables(loss.name)
    sd.setTrainingConfig(TrainingConfig(
        updater=Adam(1e-4),
        computeDtype="HALF" if args.dtype == "HALF" else None))

    if args.listener:
        from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
        sd.listeners = [ScoreIterationListener(printIterations=10)]

    rng = np.random.default_rng(0)
    batch = {in_name: rng.integers(0, V, (B, T)).astype(np.int32),
             "targets": rng.integers(0, V, (B, T)).astype(np.int32)}
    # ONE fit call per timing window: fit() bulk-syncs its loss history once
    # at the end, so steps inside a call pipeline asynchronously — a
    # fit-per-step loop pays a full device->host round-trip through the
    # tunnel every step (measured 130 ms/step vs ~30 ms compute at these
    # shapes, BASELINE.md round 4)
    sd.fit([batch] * warmup)
    # median of 3 timing windows, mirroring bench.py: the first post-warmup
    # fit window pays a one-off multi-second transient (measured identically
    # with and without listeners) and the tunnel adds per-window noise —
    # a single window reports the transient, the median reports steady state
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        hist = sd.fit([batch] * steps)
        dts.append(time.perf_counter() - t0)
        assert len(hist) == steps
    dt = sorted(dts)[1]

    tokens_per_sec = B * T * steps / dt
    from deeplearning4j_tpu.profiler.profiler import (
        MFU_BASIS, mfu as _mfu, transformer_flops_per_token)
    n_emb = V * H + T * H
    flops_per_token = transformer_flops_per_token(
        n_param - n_emb + H * V, L, H, T)
    peak = _peak_flops(jax.devices()[0]) if on_tpu else 1e12
    mfu = _mfu(tokens_per_sec, flops_per_token, peak)
    print(json.dumps({
        "metric": "bert_base_tf_import_finetune_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec",
        "dtype": args.dtype,
        "listener": bool(args.listener),
        "mfu": round(mfu, 4),
        "mfu_basis": MFU_BASIS,
        "vs_baseline": round(mfu / 0.35, 4),
    }))


if __name__ == "__main__":
    main()
