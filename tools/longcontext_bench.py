"""Long-context streamed flash-attention benchmark (the round-5 A/B harness).

Methodology (held constant across every variant so deltas are causal): 4
serially-chained layer applications inside ONE jit executable (output feeds
the next layer's q — residuals carry grad through the whole chain), grad
through the chain, T=8192 causal bf16, B=2 / H=12 / D=64 (the BASELINE.md
long-context configuration). The chain amortizes the axon tunnel's ~5 ms
per-dispatch floor the same way tools/attention_roofline.py does.

Prints one JSON report; commit the numbers into BASELINE.md.
Usage: python tools/longcontext_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

B, H, T, D = 2, 12, 8192, 64
CHAIN = 4
STEPS, WARMUP = 5, 2


def _sync(x):
    leaves = jax.tree.leaves(x)
    return float(jnp.sum(leaves[0]))


def _time(fn, *args):
    for _ in range(WARMUP):
        out = fn(*args)
    _sync(out)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        _sync(out)
        dts.append((time.perf_counter() - t0) / (STEPS * CHAIN))
    return sorted(dts)[1]


def _flops(fwd_bwd: bool) -> float:
    # causal halves the score volume; fwd = QK^T + PV = 4*B*H*T^2*D*0.5;
    # bwd recomputes s and adds dv/dp/ds->dq/dk dots ~ 2.5x fwd
    f = 4 * B * H * T * T * D * 0.5
    return f * 3.5 if fwd_bwd else f


def main():
    assert jax.default_backend() != "cpu", "bench runs on the real TPU"
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)) * 0.1, jnp.bfloat16)
               for _ in range(3))
    g = jnp.asarray(rng.normal(size=(B, H, T, D)) * 0.1, jnp.bfloat16)
    report = {"device": str(jax.devices()[0]), "B": B, "H": H, "T": T, "D": D,
              "chain": CHAIN, "results": []}

    def add(name, sec, fwd_bwd):
        tf = _flops(fwd_bwd) / sec / 1e12
        report["results"].append(
            {"variant": name, "ms_per_layer": round(sec * 1e3, 3),
             "achieved_tflops": round(tf, 2)})
        print(f"  {name}: {sec*1e3:.2f} ms/layer  ->  {tf:.1f} TF/s",
              flush=True)

    def chain(apply):
        def fn(q, k, v):
            def body(i, acc):
                return apply(acc, k, v)
            return jax.lax.fori_loop(0, CHAIN, body, q)
        # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
        return jax.jit(fn)

    def chain_grad(apply):
        def loss(q, k, v):
            def body(i, acc):
                return apply(acc, k, v)
            out = jax.lax.fori_loop(0, CHAIN, body, q)
            return jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32))
        # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    for bq, bk in ((128, 128), (256, 256), (512, 512), (1024, 1024),
                   (1024, 512), (512, 1024), (2048, 512)):
        apply = lambda q, k, v, a=bq, b=bk: flash_attention(
            q, k, v, True, a, b)
        tag = f"bq{bq}_bk{bk}"
        add(f"streamed_fwd_{tag}", _time(chain(apply), q, k, v), False)
        add(f"streamed_fwdbwd_{tag}", _time(chain_grad(apply), q, k, v), True)

    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
