"""``terminal-exactly-once``: every request terminal must carry its
accounting.

PR 5's hardest review round established the invariant: a request's
terminal (future resolved, handle finished/failed) must be delivered
exactly once AND recorded exactly once — trace finish, SLO window
outcome, rejection counters, per-tenant attribution — via
``_finish_request`` / the admission hooks / ``_shed_typed``. A raw
``future.set_result`` / ``set_exception`` / ``handle._fail`` /
``handle._finish`` anywhere else is how a new code path silently drops
out of ``/api/slo`` and ``rejections_by_reason``.

The rule: a raw terminal call is a finding unless

- it sits inside an allowlisted class — ``GenerationHandle`` (the
  delivery primitive itself) or ``AdmissionController`` (whose
  shed/close/cancel paths route accounting through the engine-installed
  ``on_shed``/``on_close_reject``/``on_cancelled`` hooks); or
- it sits in a function named in the allowlist (``_shed_typed``); or
- the SAME function also calls an accounting entry point
  (``_finish_request`` / ``_count_shed`` / ``_count_close_reject`` /
  ``_count_cancelled`` / ``_finish_stream``) — the paired-delivery
  shape every engine terminal uses.

Deliberately-unaccounted futures (e.g. the shared-prefix registration
rendezvous, which is not a request terminal) carry per-site
``# analysis: ok terminal-exactly-once — why`` suppressions.
"""
from __future__ import annotations

import ast
from typing import Optional, Set

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, iter_functions,
    scoped_walk,
)

TERMINAL_ATTRS = {"set_result", "set_exception"}
HANDLE_TERMINAL_ATTRS = {"_fail", "_finish"}
ALLOWED_CLASSES = {"GenerationHandle", "AdmissionController"}
ALLOWED_FUNCS = {"_shed_typed"}
ACCOUNTING_CALLEES = {"_finish_request", "_count_shed",
                      "_count_close_reject", "_count_cancelled",
                      "_finish_stream"}


def _is_terminal_call(node: ast.Call) -> Optional[str]:
    """The terminal kind when this call delivers one, else None."""
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    if attr in TERMINAL_ATTRS:
        return attr
    if attr in HANDLE_TERMINAL_ATTRS:
        recv = attr_chain(node.func.value) or ""
        last = recv.rsplit(".", 1)[-1].lower()
        if "handle" in last:
            return f"handle.{attr}"
    return None


def _accounting_calls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in scoped_walk(fn):
        if isinstance(node, ast.Call):
            chain = call_name(node)
            if chain is None:
                continue
            last = chain.rsplit(".", 1)[-1]
            if last in ACCOUNTING_CALLEES:
                out.add(last)
            elif last == "finish" and "trace" in chain.lower():
                out.add("trace.finish")
    return out


class TerminalExactlyOnceChecker(Checker):
    rule = "terminal-exactly-once"
    description = ("raw future/handle terminals outside the allowlisted "
                   "accounting paths")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            for qual, fn, cls in iter_functions(sf.tree):
                if cls is not None and cls.name in ALLOWED_CLASSES:
                    continue
                if fn.name in ALLOWED_FUNCS:
                    continue
                accounting = None   # computed lazily per function
                for node in scoped_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    kind = _is_terminal_call(node)
                    if kind is None:
                        continue
                    if accounting is None:
                        accounting = _accounting_calls(fn)
                    if accounting:
                        continue
                    yield unit.finding(
                        sf, self.rule, node,
                        f"raw terminal {kind}() in {qual} with no "
                        f"accounting call in the same function — route "
                        f"through _finish_request/_shed_typed (or the "
                        f"admission hooks) so the terminal reaches the "
                        f"SLO windows, traces and rejections_by_reason")
