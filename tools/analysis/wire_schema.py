"""``wire-schema-drift``: wire dataclasses must survive rolling
upgrades.

The cluster tier ships dataclasses over HTTP (``HostStatus`` today, the
RPC envelope next — ROADMAP item 1), and PR 10's review rounds already
caught one wire asymmetry by hand (the heartbeat ``seq`` field). The
contract, encoded here before the fleet goes cross-host:

A **wire dataclass** — any ``@dataclass`` that defines BOTH a
serializer (``to_dict``/``to_json``) and a deserializer classmethod
(``from_dict``/``from_json``) — must satisfy:

1. **Version field.** A field whose name contains ``version``
   (``wire_version``, ``schema_version``) so a receiver can branch on
   format changes during a rolling upgrade instead of guessing from
   field shapes.
2. **Symmetric field sets.** A serializer that builds a dict literal
   must emit every declared field and no unknown keys
   (``dataclasses.asdict(self)`` covers all fields by construction).
   A deserializer that constructs explicitly (``cls(a=d["a"], ...)``)
   must read every field that has NO default — defaulted fields may be
   absent from old senders' payloads, which is exactly how new fields
   roll out.
3. **Unknown-field tolerance.** The deserializer must not splat the
   raw payload (``cls(**d)``) — a NEWER sender's extra field would
   crash an older receiver mid-upgrade. The sanctioned idiom filters
   to declared fields first (``{k: v for k, v in d.items() if k in
   known}``, ``known`` from ``dataclasses.fields``).

Classes with only one side of the pair (e.g. ``QosPolicy.to_dict``,
a report-only payload) are not wire dataclasses and are skipped.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, string_value,
)

SERIALIZERS = {"to_dict", "to_json"}
DESERIALIZERS = {"from_dict", "from_json"}


def _is_dataclass_decorated(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = attr_chain(target)
        if chain is not None and chain.rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


def _dataclass_fields(cls: ast.ClassDef) -> List[Tuple[str, bool]]:
    """[(field name, has_default)] from annotated class-body targets
    (ClassVar / init=False subtleties are out of scope for wire types,
    which keep to plain fields)."""
    out = []
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            ann = ast.unparse(node.annotation) if hasattr(ast, "unparse") \
                else ""
            if "ClassVar" in ann:
                continue
            out.append((node.target.id, node.value is not None))
    return out


def _find_method(cls: ast.ClassDef, names: Set[str]) -> Optional[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name in names:
            return node
    return None


def _uses_asdict(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = call_name(node) or ""
            if chain.rsplit(".", 1)[-1] == "asdict":
                return True
    return False


def _literal_dict_keys(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """Constant keys when the serializer's payload is built as
    TOP-LEVEL dict literals (the build-then-patch idiom
    ``d = {...}; d["x"] = ...`` counts both); None when no literal dict
    exists. Dicts nested as VALUES inside another dict are payload
    content, not payload keys — counting them would both fabricate
    unknown-key findings and mask a genuinely unserialized field whose
    name happens to appear in a nested sub-dict (the exact asymmetry
    this rule exists to catch)."""
    nested: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for v in node.values:
                for sub in ast.walk(v):
                    if isinstance(sub, ast.Dict):
                        nested.add(id(sub))
    keys: Optional[Set[str]] = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict) and id(node) not in nested:
            if keys is None:
                keys = set()
            for k in node.keys:
                s = string_value(k) if k is not None else None
                if s is not None:
                    keys.add(s)
    if keys is None:
        return None
    # second pass: d["extra"] = ... patches after the literal (walk
    # order visits the outer Assign statements before the Dict child,
    # so this cannot fold into the loop above); only simple
    # ``name["key"]`` targets — ``d["a"]["b"]`` writes into a nested
    # payload, not a top-level key
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name):
                    s = string_value(tgt.slice)
                    if s is not None:
                        keys.add(s)
    return keys


def _splats_raw_param(fn: ast.FunctionDef) -> Optional[ast.Call]:
    """The ``cls(**d)`` call when the deserializer splats a raw
    parameter into the constructor, else None. A ``**`` operand that is
    a locally-built dict (filtered/transformed) is fine."""
    params = {a.arg for a in fn.args.args} | {a.arg for a in
                                              fn.args.kwonlyargs}
    # locals assigned in the body are transformed values, not the raw
    # payload — ``kw = {k: v ... if k in known}; return cls(**kw)``
    assigned = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    assigned.add(tgt.id)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg is None and isinstance(kw.value, ast.Name) \
                    and kw.value.id in params and kw.value.id not in assigned:
                return node
    return None


def _read_keys(fn: ast.FunctionDef) -> Set[str]:
    """Constant keys the deserializer reads: ``d["x"]``, ``d.get("x")``,
    ``kw["x"] = ...`` and keyword names in an explicit ``cls(x=...)``
    construction."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript):
            s = string_value(node.slice)
            if s is not None:
                keys.add(s)
        elif isinstance(node, ast.Call):
            chain = call_name(node) or ""
            if chain.rsplit(".", 1)[-1] == "get" and node.args:
                s = string_value(node.args[0])
                if s is not None:
                    keys.add(s)
            elif chain in ("cls", ""):
                for kw in node.keywords:
                    if kw.arg is not None:
                        keys.add(kw.arg)
    return keys


class WireSchemaDriftChecker(Checker):
    rule = "wire-schema-drift"
    description = ("wire dataclasses (paired to_dict/from_dict) must "
                   "carry a version field, serialize every declared "
                   "field, and tolerate unknown fields on receive")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                if not _is_dataclass_decorated(node):
                    continue
                ser = _find_method(node, SERIALIZERS)
                deser = _find_method(node, DESERIALIZERS)
                if ser is None or deser is None:
                    continue
                yield from self._check_wire_class(unit, sf, node, ser,
                                                  deser)

    def _check_wire_class(self, unit, sf, cls, ser, deser):
        fields = _dataclass_fields(cls)
        names = {n for n, _ in fields}

        # 1. version field for rolling upgrades
        if not any("version" in n for n in names):
            yield unit.finding(
                sf, self.rule, cls,
                f"wire dataclass {cls.name} has no version field — add "
                f"a defaulted ``wire_version: int = 1`` so receivers "
                f"can branch on format changes during a rolling upgrade "
                f"(see HostStatus)")

        # 2. serializer symmetry
        if not _uses_asdict(ser):
            keys = _literal_dict_keys(ser)
            if keys is not None:
                for n in sorted(names - keys):
                    yield unit.finding(
                        sf, self.rule, ser,
                        f"{cls.name}.{ser.name} never serializes field "
                        f"{n!r} — the receiver's {deser.name} would "
                        f"silently default it (the PR 10 heartbeat-seq "
                        f"asymmetry class)")
                for k in sorted(keys - names):
                    yield unit.finding(
                        sf, self.rule, ser,
                        f"{cls.name}.{ser.name} emits key {k!r} which is "
                        f"not a declared field — receivers filtering to "
                        f"dataclasses.fields() drop it on the floor")

        # 3. deserializer: unknown-field tolerance + required coverage
        splat = _splats_raw_param(deser)
        if splat is not None:
            yield unit.finding(
                sf, self.rule, splat,
                f"{cls.name}.{deser.name} splats the raw payload into "
                f"the constructor — a newer sender's extra field crashes "
                f"this receiver mid-rolling-upgrade; filter to known "
                f"fields first ({{k: v for k, v in d.items() if k in "
                f"known}})")
        else:
            read = _read_keys(deser)
            # a fields()-driven filter covers everything by construction
            covers_all = any(
                isinstance(n, ast.Call)
                and (call_name(n) or "").rsplit(".", 1)[-1] == "fields"
                for n in ast.walk(deser))
            if not covers_all:
                for n, has_default in fields:
                    if not has_default and n not in read:
                        yield unit.finding(
                            sf, self.rule, deser,
                            f"{cls.name}.{deser.name} never reads "
                            f"required field {n!r} — construction "
                            f"cannot succeed / the field silently "
                            f"drops off the wire")
