"""Repo-specific static analysis for the serving stack's hand-maintained
contracts (Engler et al., *Bugs as Deviant Behavior*, SOSP'01; Bessey et
al., *A Few Billion Lines of Code Later*, CACM'10).

Seven PRs of review hardening kept catching the same defect classes by
hand: blocking calls under the admission lock, use-after-donate on the
ONE donated decode executable, terminal-reason taxonomy drift, raw
future terminals that skip SLO/trace/metrics accounting, and stray
``jax.jit`` callsites that break the ``len(buckets)+1`` compiled-
signature bound. This package encodes those invariants as AST checkers
(stdlib only — no third-party deps) that run in tier-1:

- :mod:`~tools.analysis.lock_discipline` — ``lock-discipline``: the
  lock-acquisition graph over ``with self._lock:``-style sites; flags
  lock-order inversions, same-lock re-acquisition (non-reentrant
  ``threading.Lock``), and blocking calls under a held lock.
- :mod:`~tools.analysis.donation` — ``donation-safety``: reads of a
  donated cache binding after the donated call with no rebuild/epoch
  guard between them (the zombie-decode bug class PRs 3/6 fixed).
- :mod:`~tools.analysis.taxonomy` — ``taxonomy-drift``: every typed
  shed's ``reason`` literal must appear exactly once in
  ``tracing.TERMINAL_REASONS`` and be countable by
  ``rejections_by_reason``.
- :mod:`~tools.analysis.terminal` — ``terminal-exactly-once``: raw
  ``future.set_result/set_exception`` / ``handle._fail/_finish`` calls
  outside the allowlisted accounting paths.
- :mod:`~tools.analysis.recompile` — ``recompile-risk``: ``jax.jit`` /
  ``pjit`` callsites inside ``serving/`` (executables must come from
  ``models/`` factories) and shape-varying array construction that
  bypasses the bucket-ladder helpers.

The v2 suite (ISSUE 11) adds the cluster-era contracts the RPC tier
multiplies, upgrades lock-discipline/donation-safety to bounded
TRANSITIVE same-class call expansion, and pairs the static lock graph
with a runtime lockdep:

- :mod:`~tools.analysis.wire_schema` — ``wire-schema-drift``: wire
  dataclasses (paired ``to_dict``/``from_dict`` like ``HostStatus``)
  must carry a version field, serialize every declared field, and
  tolerate unknown fields on receive (rolling upgrades).
- :mod:`~tools.analysis.deadline` — ``deadline-propagation``: a
  function accepting a ``timeout``/``deadline`` parameter must thread
  it through submit-shaped forwarding calls.
- :mod:`~tools.analysis.metrics_drift` — ``metrics-drift``:
  ``ServingMetrics`` attribute references, declared names, exports,
  and ``ui/server.py`` endpoint keys must agree.
- :mod:`~tools.analysis.exception_chaining` — ``exception-chaining``:
  ``raise X(...)`` inside ``except`` without ``from`` loses the cause
  the taxonomy and crash dumps depend on.
- :mod:`~tools.analysis.lockdep` — RUNTIME lock-order validation
  (Eraser/Linux-lockdep style): instrumented ``threading`` primitives
  record the dynamic acquisition graph while the chaos suite runs;
  the differential against ``lock_discipline.static_lock_graph`` is
  drift-gated via the checked-in ``tools/analysis/lockgraph.json``.

CLI: ``python -m tools.analysis <paths...> [--json] [--baseline FILE]
[--write-baseline] [--rules r1,r2] [--changed-only [--base-ref REF]]``.
Per-site suppressions are ``# analysis: ok <rule> — why`` comments;
bulk grandfathering lives in a checked-in baseline file
(``tools/analysis/baseline.json``).
"""
from tools.analysis.core import (  # noqa: F401
    AnalysisUnit, Baseline, Checker, Finding, Report, all_checkers,
    analyze_paths, analyze_sources,
)

__all__ = ["AnalysisUnit", "Baseline", "Checker", "Finding", "Report",
           "all_checkers", "analyze_paths", "analyze_sources"]
