"""Runtime lockdep: dynamic lock-order validation for the serving stack.

The static ``lock-discipline`` checker sees lexical nesting plus
bounded same-class call expansion — by construction it is blind to
acquisition orders that only exist DYNAMICALLY: an engine thread
holding ``GenerationEngine._wd_lock`` while the admission controller's
``_cv`` fires a callback, a metrics counter lock taken under a
scheduler lock three objects away. This module is the other half, in
the style of Eraser (Savage et al., SOSP'97) and Linux lockdep:
instrumented wrappers for ``threading.Lock`` / ``RLock`` /
``Condition`` record, while the real tier-1 chaos/stress tests run,

- the per-thread **acquisition-order graph** over lock CLASSES (locks
  are classed by creation site, lockdep-style: instance class +
  attribute name, ``GenerationEngine._wd_lock``, so every engine
  instance maps to one node),
- **held-lock blocking calls** — ``Condition.wait`` entered while
  OTHER locks are held (the dynamic analogue of the static two-lock
  sleep rule), and
- **hold times** (max + total per class, acquire-contention wait max).

:func:`differential` then cross-checks the dynamic graph against
``lock_discipline.static_lock_graph``: dynamic-only edges expose
call-indirection blind spots in the static checker (each must be
waived-with-why in ``tools/analysis/lockgraph.json`` or fixed), and a
cycle in the MERGED graph is a potential deadlock neither side can
prove safe alone.

Opt-in and bitwise-inert when off: nothing is patched at import time;
``install()`` swaps the ``threading`` factories and ``uninstall()``
restores them. Locks created from NON-repo code (pytest, stdlib
internals) get real primitives — zero overhead outside the
``deeplearning4j_tpu`` package.

Pytest plugin (THE intended entry point)::

    LOCKDEP_REPORT=/tmp/lockdep.json \\
        python -m pytest tests/test_resilience.py -q -m 'not slow' \\
        -p tools.analysis.lockdep

``pytest_configure`` installs the wrappers before test modules import,
``pytest_unconfigure`` writes the JSON report and restores threading.

CLI::

    python -m tools.analysis.lockdep --report /tmp/lockdep.json          # diff
    python -m tools.analysis.lockdep --report /tmp/lockdep.json --update # regen

``--update`` folds newly-observed dynamic edges into
``lockgraph.json`` (waivers and their whys are preserved); the plain
run prints the differential and exits 1 on unwaived dynamic-only edges
or merged-graph cycles.
"""
from __future__ import annotations

import json
import linecache
import os
import re
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

#: Only locks created from files under these path fragments are
#: tracked — everything else passes through as a real primitive.
REPO_MARKERS = (os.sep + "deeplearning4j_tpu" + os.sep,)

DEFAULT_GRAPH = os.path.join(os.path.dirname(__file__), "lockgraph.json")

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_ASSIGN_RE = re.compile(r"\s*(self\.)?([A-Za-z_]\w*)\s*[:=]")


class _State:
    """Global lockdep state. Mutations ride a REAL lock (the
    instrumented factories are never active inside this module)."""

    def __init__(self):
        self.enabled = False
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        # (src class, dst class) -> count
        self.edges: Dict[Tuple[str, str], int] = {}
        # same-class nesting (two INSTANCES of one class held together):
        # not an order edge — a self-loop would fail every cycle check —
        # but worth surfacing in the report
        self.same_class: Dict[str, int] = {}
        # class -> [n_acquires, max_hold_s, total_hold_s, max_wait_s]
        self.holds: Dict[str, List[float]] = {}
        # Condition.wait entered while holding other locks:
        # (waited-on class, tuple of held classes) -> count
        self.waits_under_lock: Dict[Tuple[str, Tuple[str, ...]], int] = {}

    # ---------------------------------------------------------- per-thread
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, key: str, obj_id: int, waited_s: float):
        st = self._stack()
        nested = any(e[1] == obj_id for e in st)
        if not nested:
            held = []
            for e in st:
                if e[0] not in held:
                    held.append(e[0])
            with self._mu:
                for h in held:
                    if h == key:
                        self.same_class[key] = \
                            self.same_class.get(key, 0) + 1
                    else:
                        self.edges[(h, key)] = \
                            self.edges.get((h, key), 0) + 1
                rec = self.holds.setdefault(key, [0, 0.0, 0.0, 0.0])
                rec[0] += 1
                if waited_s > rec[3]:
                    rec[3] = waited_s
        st.append((key, obj_id, time.perf_counter(), nested))

    def on_release(self, key: str, obj_id: int):
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][1] == obj_id:
                _k, _oid, t0, nested = st.pop(i)
                if not nested:
                    held_s = time.perf_counter() - t0
                    with self._mu:
                        rec = self.holds.setdefault(key, [0, 0.0, 0.0, 0.0])
                        if held_s > rec[1]:
                            rec[1] = held_s
                        rec[2] += held_s
                return

    def on_wait(self, key: str, obj_id: int):
        """Condition.wait entry: the condition's lock is released for
        the wait — pop it; record the held-lock blocking call if other
        locks stay held (st entries for OTHER objects)."""
        st = self._stack()
        others = tuple(sorted({e[0] for e in st if e[1] != obj_id}))
        if others:
            with self._mu:
                k = (key, others)
                self.waits_under_lock[k] = \
                    self.waits_under_lock.get(k, 0) + 1
        self.on_release(key, obj_id)

    def on_wait_done(self, key: str, obj_id: int):
        # re-acquisition after the wait: same edge semantics as a fresh
        # acquire — the re-take happens while the OTHER held locks are
        # still held, so order edges are recorded again (idempotent)
        self.on_acquire(key, obj_id, 0.0)

    # ------------------------------------------------------------- reading
    def reset(self):
        with self._mu:
            self.edges.clear()
            self.same_class.clear()
            self.holds.clear()
            self.waits_under_lock.clear()

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "schema_version": 1,
                "edges": [{"src": a, "dst": b, "count": n}
                          for (a, b), n in sorted(self.edges.items())],
                "same_class_nesting": dict(sorted(
                    self.same_class.items())),
                "holds": {k: {"acquires": int(v[0]),
                              "max_hold_ms": round(v[1] * 1e3, 3),
                              "total_hold_ms": round(v[2] * 1e3, 3),
                              "max_acquire_wait_ms": round(v[3] * 1e3, 3)}
                          for k, v in sorted(self.holds.items())},
                "waits_under_lock": [
                    {"wait_on": k, "holding": list(held), "count": n}
                    for (k, held), n in sorted(
                        self.waits_under_lock.items())],
            }


_STATE = _State()


# --------------------------------------------------------------------------
# Lock classing: creation-site naming
# --------------------------------------------------------------------------
def _creation_key() -> Optional[str]:
    """The lock-class key for a primitive being created RIGHT NOW, from
    the first repo frame up the stack: ``InstanceClass._attr`` when the
    creation line is a ``self._attr = threading.Lock()`` assignment
    inside a method, ``module.py:NAME`` for module-level locks, None
    (-> untracked real primitive) when no repo frame exists."""
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        if any(m in fname for m in REPO_MARKERS):
            break
        f = f.f_back
    if f is None:
        return None
    line = linecache.getline(f.f_code.co_filename, f.f_lineno)
    m = _ASSIGN_RE.match(line)
    attr = m.group(2) if m else None
    if m and m.group(1):   # self._attr = ...
        slf = f.f_locals.get("self")
        if slf is not None:
            return f"{type(slf).__name__}.{attr}"
    base = os.path.basename(f.f_code.co_filename)
    if attr:
        return f"{base}:{attr}"
    return f"{base}:{f.f_code.co_name}:{f.f_lineno}"


# --------------------------------------------------------------------------
# Instrumented primitives
# --------------------------------------------------------------------------
class _TrackedBase:
    _ld_key: str

    def __repr__(self):
        return f"<lockdep {type(self).__name__} {self._ld_key} " \
               f"wrapping {self._ld_inner!r}>"


class _TrackedLock(_TrackedBase):
    def __init__(self, inner, key: str):
        self._ld_inner = inner
        self._ld_key = key

    def acquire(self, blocking=True, timeout=-1):
        t0 = time.perf_counter()
        got = self._ld_inner.acquire(blocking, timeout)
        if got:
            _STATE.on_acquire(self._ld_key, id(self),
                              time.perf_counter() - t0)
        return got

    def release(self):
        _STATE.on_release(self._ld_key, id(self))
        self._ld_inner.release()

    def locked(self):
        return self._ld_inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _TrackedRLock(_TrackedLock):
    # reentrancy rides _State's nested-detection (same obj id already on
    # the thread's stack -> no edges, symmetric push/pop)
    def locked(self):   # RLock has no .locked() before 3.12; mirror it
        inner = self._ld_inner
        return inner.locked() if hasattr(inner, "locked") else False


class _TrackedCondition(_TrackedBase):
    """A real Condition over the REAL underlying lock, with acquisition
    tracking keyed to the lock's class. ``threading.Condition(lock)``
    over an instrumented lock shares that lock's identity — acquiring
    the condition IS acquiring the lock, so the graph sees one node."""

    def __init__(self, inner_cond, key: str, obj_id: Optional[int] = None):
        self._ld_inner = inner_cond
        self._ld_key = key
        self._ld_obj = obj_id if obj_id is not None else id(self)

    def acquire(self, *args, **kwargs):
        t0 = time.perf_counter()
        got = self._ld_inner.acquire(*args, **kwargs)
        if got:
            _STATE.on_acquire(self._ld_key, self._ld_obj,
                              time.perf_counter() - t0)
        return got

    def release(self):
        _STATE.on_release(self._ld_key, self._ld_obj)
        self._ld_inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        _STATE.on_wait(self._ld_key, self._ld_obj)
        try:
            return self._ld_inner.wait(timeout)
        finally:
            _STATE.on_wait_done(self._ld_key, self._ld_obj)

    def wait_for(self, predicate, timeout=None):
        _STATE.on_wait(self._ld_key, self._ld_obj)
        try:
            return self._ld_inner.wait_for(predicate, timeout)
        finally:
            _STATE.on_wait_done(self._ld_key, self._ld_obj)

    def notify(self, n=1):
        self._ld_inner.notify(n)

    def notify_all(self):
        self._ld_inner.notify_all()


# --------------------------------------------------------------------------
# Factories + install/uninstall
# --------------------------------------------------------------------------
def _lock_factory():
    key = _creation_key()
    if key is None:
        return _REAL_LOCK()
    return _TrackedLock(_REAL_LOCK(), key)


def _rlock_factory():
    key = _creation_key()
    if key is None:
        return _REAL_RLOCK()
    return _TrackedRLock(_REAL_RLOCK(), key)


def _condition_factory(lock=None):
    if isinstance(lock, _TrackedLock):
        # share the wrapped lock's identity: Condition(self._lock)
        inner = _REAL_CONDITION(lock._ld_inner)
        return _TrackedCondition(inner, lock._ld_key, id(lock))
    if lock is not None:
        return _REAL_CONDITION(lock)
    key = _creation_key()
    if key is None:
        return _REAL_CONDITION()
    return _TrackedCondition(_REAL_CONDITION(_REAL_RLOCK()), key)


def install():
    """Patch the ``threading`` factories. Idempotent. Locks created
    BEFORE install stay real (uninstrumented) — install early (the
    pytest plugin installs at configure time, before test imports)."""
    if _STATE.enabled:
        return
    _STATE.enabled = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory


def uninstall():
    if not _STATE.enabled:
        return
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _STATE.enabled = False


def reset():
    _STATE.reset()


def snapshot() -> dict:
    return _STATE.snapshot()


def write_report(path: str):
    with open(path, "w") as f:
        json.dump(_STATE.snapshot(), f, indent=2, sort_keys=True)
        f.write("\n")


class capture:
    """Context manager for in-process use::

        with lockdep.capture() as state:
            ...build engines, run traffic...
        graph = state.snapshot()

    Construct the objects under test INSIDE the block — locks created
    before it are not instrumented.
    """

    def __enter__(self):
        install()
        reset()
        return _STATE

    def __exit__(self, *exc):
        uninstall()
        return False


# --------------------------------------------------------------------------
# Differential vs the static graph
# --------------------------------------------------------------------------
def load_graph(path: str = DEFAULT_GRAPH) -> dict:
    with open(path) as f:
        return json.load(f)


def _edge_waived(edge: Tuple[str, str], waivers: List[dict]) -> Optional[str]:
    """The why when ``edge`` matches a waiver (entries support ``*``
    wildcards per endpoint — metrics leaf locks would otherwise need
    one entry per holder class), else None."""
    for w in waivers:
        src, dst = w.get("edge", (None, None))
        if (src == "*" or src == edge[0]) and (dst == "*" or dst == edge[1]):
            return w.get("why", "(no reason given)")
    return None


def find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles in the merged graph (Tarjan SCCs; any SCC with
    more than one node, or a self-loop, is reported as its sorted node
    list — enough to name the deadlock suspects)."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (the graph is small, but recursion depth
        # should not depend on it)
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or node in adj.get(node, ()):
                    out.append(sorted(scc))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return out


def differential(dynamic: dict, graph: dict) -> dict:
    """Cross-check one dynamic report against the checked-in graph.

    ``dynamic`` is a :func:`snapshot` / ``LOCKDEP_REPORT`` payload;
    ``graph`` is ``lockgraph.json`` (static edges + recorded dynamic
    edges + dynamic-only waivers). Returns::

        {"dynamic_only": [...],          # observed, absent statically
         "same_class_nesting": [...],    # two instances of K nested
         "unwaived": [...],              # dynamic-only/nesting, NO waiver
         "static_only": [...],           # static edges this run missed
         "cycles": [[node, ...], ...],   # merged-graph cycles
         "ok": bool}

    Same-class nesting gates as a waivable ``[K, K]`` pseudo-edge: the
    class-level graph cannot distinguish a consistent instance order
    (A1 before A2, always) from a two-instance ABBA deadlock, so a
    human must certify the instance-level order — the lockdep
    nest-annotation analogue. It is NOT merged into the cycle check
    (a self-loop would condemn every consistent nesting).

    ``static_only`` is informational (a run that skips a test simply
    does not exercise every edge); ``unwaived`` and ``cycles`` are the
    failures the drift gate asserts empty.
    """
    dyn_edges = {(e["src"], e["dst"]) for e in dynamic.get("edges", [])}
    static_edges = {tuple(e) for e in
                    graph.get("static", {}).get("edges", [])}
    recorded = {tuple(e["edge"]) for e in
                graph.get("dynamic", {}).get("edges", [])}
    waivers = graph.get("dynamic_only_waivers", [])
    dynamic_only = sorted(dyn_edges - static_edges)
    same_class = sorted(dynamic.get("same_class_nesting", {}))
    unwaived = [e for e in dynamic_only + [(k, k) for k in same_class]
                if _edge_waived(e, waivers) is None]
    merged = static_edges | dyn_edges | recorded
    cycles = find_cycles(merged)
    return {
        "dynamic_only": [list(e) for e in dynamic_only],
        "same_class_nesting": same_class,
        "unwaived": [list(e) for e in unwaived],
        "static_only": sorted(list(e)
                              for e in static_edges - dyn_edges),
        "cycles": cycles,
        "ok": not unwaived and not cycles,
    }


# --------------------------------------------------------------------------
# Pytest plugin: ``pytest -p tools.analysis.lockdep``
# --------------------------------------------------------------------------
def pytest_configure(config):
    install()


def pytest_unconfigure(config):
    path = os.environ.get("LOCKDEP_REPORT", "")
    if path:
        write_report(path)
    uninstall()


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------
#: Repo-root-anchored (this file lives at tools/analysis/lockdep.py) so
#: --update run from any CWD regenerates against the real tree instead
#: of silently writing an empty static graph.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
STATIC_SCOPE = tuple(os.path.join(_REPO_ROOT, p) for p in (
    "deeplearning4j_tpu/serving", "deeplearning4j_tpu/models",
    "deeplearning4j_tpu/ops", "tools",
    "deeplearning4j_tpu/ui/server.py"))


def regenerate_static(graph_path: str = DEFAULT_GRAPH,
                      scope=STATIC_SCOPE) -> dict:
    """Recompute the static half in-place (waivers + recorded dynamic
    edges preserved); returns the updated graph dict."""
    from tools.analysis.lock_discipline import static_lock_graph

    live = [p for p in scope if os.path.exists(p)]
    if not live:
        raise RuntimeError(f"no static-scope paths exist under "
                           f"{_REPO_ROOT} — refusing to write an empty "
                           f"static graph")
    graph = load_graph(graph_path) if os.path.exists(graph_path) else {
        "schema_version": 1, "static": {}, "dynamic": {"edges": []},
        "dynamic_only_waivers": []}
    graph["static"] = static_lock_graph(live)
    with open(graph_path, "w") as f:
        json.dump(graph, f, indent=2, sort_keys=True)
        f.write("\n")
    return graph


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tools.analysis.lockdep",
        description="Differential of a runtime lockdep report against "
                    "the checked-in lock graph.")
    p.add_argument("--report", help="LOCKDEP_REPORT JSON from a "
                                    "-p tools.analysis.lockdep test run")
    p.add_argument("--graph", default=DEFAULT_GRAPH,
                   help="lockgraph.json (default: tools/analysis/)")
    p.add_argument("--update", action="store_true",
                   help="fold the report's observed edges into the "
                        "graph's dynamic section and regenerate the "
                        "static section (waivers preserved)")
    args = p.parse_args(argv)
    if args.update:
        graph = regenerate_static(args.graph)
        if args.report:
            with open(args.report) as f:
                dyn = json.load(f)
            known = {tuple(e["edge"]): e
                     for e in graph.get("dynamic", {}).get("edges", [])}
            for e in dyn.get("edges", []):
                key = (e["src"], e["dst"])
                if key in known:
                    known[key]["count"] = max(known[key].get("count", 0),
                                              e.get("count", 0))
                else:
                    known[key] = {"edge": list(key),
                                  "count": e.get("count", 0)}
            nesting = dict(graph.get("dynamic", {}).get(
                "same_class_nesting", {}))
            for k, n in dyn.get("same_class_nesting", {}).items():
                nesting[k] = max(nesting.get(k, 0), n)
            graph["dynamic"] = {"edges": sorted(
                known.values(), key=lambda d: d["edge"]),
                "same_class_nesting": dict(sorted(nesting.items()))}
            with open(args.graph, "w") as f:
                json.dump(graph, f, indent=2, sort_keys=True)
                f.write("\n")
        print(f"updated {args.graph}")
        return 0
    if not args.report:
        p.error("--report is required (or --update)")
    with open(args.report) as f:
        dyn = json.load(f)
    diff = differential(dyn, load_graph(args.graph))
    print(json.dumps(diff, indent=2))
    return 0 if diff["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
