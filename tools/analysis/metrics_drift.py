"""``metrics-drift``: ``ServingMetrics`` and its consumers agree.

Every signal the stack emits hangs off ONE class
(``serving/metrics.py ServingMetrics``); every consumer — the engines'
``self.metrics.<attr>`` recording sites, the ``snapshot()`` roll-up,
``ui/server.py``'s ``/api/*`` endpoints — names those attributes or
the snapshot keys by string. Nothing ties the two sides together until
a dashboard quietly reads zeros. The checker closes the loop:

1. **References resolve.** Any ``<recv>.metrics.X`` / ``<recv>._metrics.X``
   attribute access in the analyzed files must name a real
   ``ServingMetrics`` attribute (metric object, method, or constant) —
   a typo'd ``metrics.request_total.inc()`` is a finding, not a
   silently-zero counter.
2. **Metrics are exported.** Every Counter/Gauge/Histogram/ReasonCounter
   the constructor defines must be READ somewhere outside ``__init__``
   (the ``snapshot()``/``counters()`` roll-ups count) — a metric nobody
   exports is drift in the other direction: recorded cost, invisible
   signal.
3. **Declared names match attributes.** ``self.X = Counter("Y")`` with
   ``X != Y`` splits the attribute vocabulary from the exported-name
   vocabulary (``snapshot()`` spreads ``counters()`` by DECLARED name;
   dashboards then chart a key no recording site mentions).
4. **Endpoint keys exist.** ``_metrics_rollup("<key>")`` calls (the
   ``/api/slo`` + ``/api/qos`` shape in ``ui/server.py``) must name a
   key ``snapshot()`` actually emits.

When no ``ServingMetrics`` class is in the analyzed file set (a run
scoped to ``models/``), the checker is silent — nothing to drift from.
"""
from __future__ import annotations

import ast
from typing import Dict, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, string_value,
)

METRIC_CTORS = {"Counter", "Gauge", "Histogram", "ReasonCounter",
                "SlidingWindowStats"}
METRICS_RECEIVERS = {"metrics", "_metrics"}
#: Recording methods on the metric primitives (Counter.inc, Gauge.set/
#: add, Histogram.observe, ReasonCounter.inc, SlidingWindowStats.record).
#: A reference consumed ONLY by these is a write site — it must not
#: satisfy rule 2's "metric is exported" check, or a counter that is
#: inc'd everywhere but never surfaced by counters()/snapshot() passes
#: silently (recorded cost, invisible signal).
WRITE_METHODS = {"inc", "set", "add", "observe", "record"}


def _find_class(unit: AnalysisUnit, name: str):
    for sf in unit.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return sf, node
    return None


class _MetricsInfo:
    """The ServingMetrics surface, from its ClassDef."""

    def __init__(self, sf, cls: ast.ClassDef):
        self.sf = sf
        self.cls = cls
        # attr -> (declared name or None, Assign node) for metric objects
        self.metric_attrs: Dict[str, Tuple[Optional[str], ast.AST]] = {}
        self.other_attrs: Set[str] = set()   # non-metric self.* + consts
        self.methods: Set[str] = set()
        self.snapshot_keys: Set[str] = set()
        for node in cls.body:
            if isinstance(node, ast.FunctionDef):
                self.methods.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.other_attrs.add(t.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                self.other_attrs.add(node.target.id)
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is not None:
            for node in ast.walk(init):
                # plain AND annotated assignments (``self.slo_windows:
                # Dict[...] = {...}`` is an AnnAssign)
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                for t in targets:
                    chain = attr_chain(t)
                    if chain is None or not chain.startswith("self.") \
                            or chain.count(".") != 1:
                        continue
                    attr = chain.split(".", 1)[1]
                    declared = self._metric_ctor(value)
                    if declared is not None:
                        self.metric_attrs[attr] = (declared, node)
                    else:
                        self.other_attrs.add(attr)
        # snapshot keys: literal dict keys in snapshot() + every counter
        # name (snapshot() spreads **self.counters() by declared name —
        # declared == attr is enforced by check 3)
        snap = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "snapshot"), None)
        if snap is not None:
            for node in ast.walk(snap):
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        s = string_value(k) if k is not None else None
                        if s is not None:
                            self.snapshot_keys.add(s)
        self.snapshot_keys |= set(self.metric_attrs)

    @staticmethod
    def _metric_ctor(value: ast.AST) -> Optional[str]:
        """The declared metric NAME when ``value`` is a
        ``Counter("name")``-style construction, "" when the ctor takes
        a non-constant name, None when not a metric ctor."""
        if not isinstance(value, ast.Call):
            return None
        chain = call_name(value) or ""
        if chain.rsplit(".", 1)[-1] not in METRIC_CTORS:
            return None
        if value.args:
            s = string_value(value.args[0])
            return s if s is not None else ""
        return ""


class MetricsDriftChecker(Checker):
    rule = "metrics-drift"
    description = ("ServingMetrics attribute references, declared metric "
                   "names, exports, and UI endpoint keys must agree")

    def check(self, unit: AnalysisUnit):
        found = _find_class(unit, "ServingMetrics")
        if found is None:
            return
        info = _MetricsInfo(*found)
        known = set(info.metric_attrs) | info.other_attrs | info.methods

        # 3. declared name matches the attribute
        for attr, (declared, node) in sorted(info.metric_attrs.items()):
            if declared and declared != attr:
                yield unit.finding(
                    info.sf, self.rule, node,
                    f"ServingMetrics.{attr} is declared as "
                    f"{declared!r} — snapshot()/dashboards export the "
                    f"declared name while recording sites use the "
                    f"attribute; keep them identical")

        # 1. references resolve  +  2. every metric is EXPORTED (read by
        # something other than a recording call). Two-pass: references
        # count per metric, write-consumptions count per metric —
        # ``self.metrics.X.inc()`` contributes one reference (the ``X``
        # attribute) AND one write (the ``inc`` attribute whose receiver
        # chain ends in ``.X``), so refs > writes iff some site reads
        # the metric (``.value``, ``to_dict()``, counters()' bare
        # enumeration, snapshot roll-ups).
        refs: Dict[str, int] = {}
        writes: Dict[str, int] = {}
        for sf in unit.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Attribute) \
                        or not isinstance(node.ctx, ast.Load):
                    continue
                recv = attr_chain(node.value)
                if recv is None:
                    continue
                parts = recv.rsplit(".", 2)
                recv_last = parts[-1]
                if recv_last in METRICS_RECEIVERS:
                    if node.attr not in known:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"{recv}.{node.attr} references a "
                            f"ServingMetrics attribute that does not "
                            f"exist — the recording silently vanishes "
                            f"(typo, or a metric that was removed "
                            f"without its call sites)")
                    elif node.attr in info.metric_attrs:
                        refs[node.attr] = refs.get(node.attr, 0) + 1
                elif recv == "self" and sf is info.sf:
                    if node.attr in info.metric_attrs and \
                            info.sf.func_at(node.lineno) != \
                            "ServingMetrics.__init__":
                        refs[node.attr] = refs.get(node.attr, 0) + 1
                elif node.attr in WRITE_METHODS and len(parts) >= 2 \
                        and recv_last in info.metric_attrs:
                    # ``<...>.metrics.X.inc`` / (in metrics.py)
                    # ``self.X.inc`` — the receiver whose last component
                    # is a metric attr and whose previous component is a
                    # metrics receiver (or bare self in metrics.py)
                    prev = parts[-2]
                    if prev in METRICS_RECEIVERS or (
                            prev == "self" and len(parts) == 2
                            and sf is info.sf):
                        writes[recv_last] = writes.get(recv_last, 0) + 1
        for attr in sorted(set(info.metric_attrs)):
            if refs.get(attr, 0) > writes.get(attr, 0):
                continue
            _, node = info.metric_attrs[attr]
            yield unit.finding(
                info.sf, self.rule, node,
                f"ServingMetrics.{attr} is only ever recorded "
                f"(inc/set/observe/...), never read outside __init__ — "
                f"it records cost nobody exports; wire it into "
                f"counters()/snapshot() or delete it")

        # 4. endpoint keys exist in the snapshot payload
        for sf in unit.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = call_name(node) or ""
                if chain.rsplit(".", 1)[-1] != "_metrics_rollup" \
                        or not node.args:
                    continue
                s = string_value(node.args[0])
                if s is not None and s not in info.snapshot_keys:
                    yield unit.finding(
                        sf, self.rule, node,
                        f"_metrics_rollup({s!r}) asks for a key "
                        f"ServingMetrics.snapshot() never emits — the "
                        f"endpoint would serve nulls for every worker")
