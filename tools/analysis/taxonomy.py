"""``taxonomy-drift``: the terminal-reason taxonomy is ONE vocabulary.

``tracing.terminal_reason`` is the single exception->taxonomy mapping;
``TERMINAL_REASONS`` is the canonical list that ``/api/slo`` error
buckets, ``rejections_by_reason`` and trace terminals all share. PR 7
added a one-off drift-guard test for its three new reasons; this
checker generalizes it into a whole-package pass:

1. ``TERMINAL_REASONS`` itself carries no duplicates.
2. Every ``RejectedError`` subclass (transitively, across the analyzed
   files) that passes a literal ``reason`` to ``super().__init__`` must
   use a reason that appears EXACTLY once in ``TERMINAL_REASONS`` —
   a new typed shed error that forgets to register its reason fails
   the lint, by construction.
3. Every literal reason string at a recording site —
   ``record_rejection("x")``, ``record_outcome("x", ...)``,
   ``_finish_request(trace, "x", ...)``, ``trace.finish("x", ...)``,
   and direct ``RejectedError("msg", "x")`` construction — must be in
   ``TERMINAL_REASONS``.
4. Every subclass reason must be COUNTABLE by ``rejections_by_reason``:
   either a literal ``record_rejection("<reason>")`` exists somewhere,
   or the package routes typed sheds dynamically (a
   ``record_rejection(<non-literal>)`` call — the shared
   ``_reject_submit``/``_shed_typed`` helpers).
5. ``BURN_REASONS`` (the SLO-burn governor's suffered-failure set) must
   be a subset of ``TERMINAL_REASONS``.

If no ``TERMINAL_REASONS`` assignment exists in the analyzed file set
(e.g. a run scoped to ``models/`` only), the taxonomy checks are
skipped — there is nothing to drift from.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, call_name, iter_functions, string_value,
)

RECORDING_CALLEES = {"record_rejection", "record_outcome"}
#: callees whose arg INDEX 1 is the reason (arg 0 is the trace)
TRACE_REASON_CALLEES = {"_finish_request"}


def _collect_terminal_reasons(unit: AnalysisUnit):
    """(source file, assignment node, [reason literals]) for the
    TERMINAL_REASONS tuple, or None when absent."""
    for sf in unit.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "TERMINAL_REASONS" not in names:
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                reasons = [string_value(e) for e in node.value.elts]
                if all(r is not None for r in reasons):
                    return sf, node, reasons
    return None


def _collect_burn_reasons(unit: AnalysisUnit):
    for sf in unit.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "BURN_REASONS" not in names:
                continue
            literals = [string_value(n) for n in ast.walk(node.value)
                        if isinstance(n, ast.Constant)]
            return sf, node, [r for r in literals if r is not None]
    return None


def _rejected_subclasses(unit: AnalysisUnit) -> List[Tuple[object, ast.ClassDef]]:
    """Every class transitively subclassing RejectedError across the
    analyzed files (matched by name — the package imports it by name
    everywhere)."""
    classes: Dict[str, Tuple[object, ast.ClassDef]] = {}
    bases: Dict[str, Set[str]] = {}
    for sf in unit.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = (sf, node)
                bases[node.name] = {
                    b.id if isinstance(b, ast.Name) else b.attr
                    for b in node.bases
                    if isinstance(b, (ast.Name, ast.Attribute))}
    rejected = {"RejectedError"}
    changed = True
    while changed:
        changed = False
        for name, bs in bases.items():
            if name not in rejected and bs & rejected:
                rejected.add(name)
                changed = True
    return [(sf, node) for name, (sf, node) in classes.items()
            if name in rejected and name != "RejectedError"]


def _subclass_reason(cls: ast.ClassDef) -> Optional[Tuple[str, ast.AST]]:
    """The literal reason a subclass stamps in its __init__ via
    ``super().__init__(msg, "reason")`` (positional or ``reason=``), or
    None when it forwards a parameter / has no __init__."""
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr == "__init__"
                        and isinstance(f.value, ast.Call)
                        and isinstance(f.value.func, ast.Name)
                        and f.value.func.id == "super"):
                    continue
                if len(call.args) >= 2:
                    s = string_value(call.args[1])
                    if s is not None:
                        return s, call
                for kw in call.keywords:
                    if kw.arg == "reason":
                        s = string_value(kw.value)
                        if s is not None:
                            return s, call
    return None


class TaxonomyDriftChecker(Checker):
    rule = "taxonomy-drift"
    description = ("typed shed reasons must appear exactly once in "
                   "tracing.TERMINAL_REASONS and be countable by "
                   "rejections_by_reason")

    def check(self, unit: AnalysisUnit):
        found = _collect_terminal_reasons(unit)
        if found is None:
            return
        tr_sf, tr_node, reasons = found
        counts: Dict[str, int] = {}
        for r in reasons:
            counts[r] = counts.get(r, 0) + 1
        for r, n in counts.items():
            if n > 1:
                yield unit.finding(
                    tr_sf, self.rule, tr_node,
                    f"TERMINAL_REASONS lists {r!r} {n} times — the "
                    f"taxonomy must carry no duplicates")
        known = set(counts)

        # literal reasons at recording sites + raw RejectedError(...)
        literal_counts: Set[str] = set()
        has_dynamic_count = False
        for sf in unit.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = call_name(node)
                last = chain.rsplit(".", 1)[-1] if chain else ""
                if last in RECORDING_CALLEES and node.args:
                    s = string_value(node.args[0])
                    if s is None:
                        if last == "record_rejection":
                            has_dynamic_count = True
                    elif s not in known:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"{last}({s!r}) uses a reason missing from "
                            f"TERMINAL_REASONS — register it there (and "
                            f"in the SLO/trace vocabulary) or reuse an "
                            f"existing reason")
                    else:
                        literal_counts.add(s)
                elif last in TRACE_REASON_CALLEES and len(node.args) >= 2:
                    s = string_value(node.args[1])
                    if s is not None and s not in known:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"{last}(..., {s!r}) uses a reason missing "
                            f"from TERMINAL_REASONS")
                elif last == "finish" and chain and "trace" in chain.lower() \
                        and node.args:
                    s = string_value(node.args[0])
                    if s is not None and s not in known:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"trace.finish({s!r}) uses a reason missing "
                            f"from TERMINAL_REASONS")
                elif last == "RejectedError" and len(node.args) >= 2:
                    s = string_value(node.args[1])
                    if s is not None and s not in known:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"RejectedError(..., {s!r}) uses a reason "
                            f"missing from TERMINAL_REASONS")

        # typed subclasses: registered exactly once + countable
        for sf, cls in _rejected_subclasses(unit):
            got = _subclass_reason(cls)
            if got is None:
                continue
            reason, site = got
            if reason not in known:
                yield unit.finding(
                    sf, self.rule, cls,
                    f"{cls.name} sheds with reason {reason!r}, which is "
                    f"not in tracing.TERMINAL_REASONS — every typed shed "
                    f"must register its reason (see MIGRATING.md)")
            elif counts[reason] != 1:
                yield unit.finding(
                    sf, self.rule, cls,
                    f"{cls.name}'s reason {reason!r} appears "
                    f"{counts[reason]} times in TERMINAL_REASONS")
            if reason in known and not has_dynamic_count \
                    and reason not in literal_counts:
                yield unit.finding(
                    sf, self.rule, cls,
                    f"{cls.name}'s reason {reason!r} is never counted: "
                    f"no record_rejection({reason!r}) literal and no "
                    f"dynamic record_rejection(exc.reason) routing "
                    f"exists — sheds of this type would vanish from "
                    f"rejections_by_reason")

        # BURN_REASONS ⊆ TERMINAL_REASONS
        burn = _collect_burn_reasons(unit)
        if burn is not None:
            b_sf, b_node, b_reasons = burn
            for r in b_reasons:
                if r not in known:
                    yield unit.finding(
                        b_sf, self.rule, b_node,
                        f"BURN_REASONS entry {r!r} is not in "
                        f"TERMINAL_REASONS — the governor would count a "
                        f"reason no terminal can ever produce")
