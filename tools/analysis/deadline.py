"""``deadline-propagation``: a forwarded request keeps its deadline.

The single-host stack threads ``timeout_ms`` end to end: submit stamps
``Request.deadline_t``, admission sheds expired heads, the SLO windows
bucket ``deadline`` terminals. The cluster tier multiplies the hops —
front door -> host handle -> remote engine (ROADMAP item 1 adds the RPC
leg) — and EVERY hop that drops the parameter turns a caller's 50 ms
budget into an unbounded wait on a remote queue: the shed happens (if
at all) at the wrong tier, with the wrong taxonomy, after the client
gave up.

The rule: a function that accepts a deadline-ish parameter (name
containing ``timeout`` or ``deadline``) and makes a submit-shaped
forwarding call (final callee name in :data:`FORWARD_CALLEES`) must
reference one of those parameters somewhere in that call's arguments —
positionally, by keyword, through a derived local (``tmo = timeout_ms
or default`` still references it at the derivation site and usually at
the call), or by splatting ``**kwargs`` it arrived in. A submit-shaped
call with no deadline reference while one was available to forward is
a finding.

Functions WITHOUT a deadline-ish parameter are not findings: the
engines' internal dispatch helpers deliberately work on already-
stamped ``Request`` objects (the deadline rides the object, not the
signature).
"""
from __future__ import annotations

import ast
from typing import Set

from tools.analysis.core import (
    AnalysisUnit, Checker, call_name, iter_functions, scoped_walk,
)

#: Final callee names that forward a request/dispatch to another
#: component. ``submit`` covers both engines and the front door;
#: ``submit_infer``/``submit_generate`` are the HostHandle RPC seam;
#: ``admit`` is the admission hop that stamps the deadline;
#: ``migrate_prefill``/``submit_migrated`` are the two-stage
#: disaggregated dispatch (serving/disagg.py over the kv.migrate
#: endpoint) — the budget must shrink across BOTH stages, never reset.
FORWARD_CALLEES = {"submit", "submit_infer", "submit_generate", "admit",
                   "migrate_prefill", "submit_migrated"}

DEADLINE_MARKERS = ("timeout", "deadline")


def _deadline_params(fn: ast.FunctionDef) -> Set[str]:
    args = list(fn.args.posonlyargs) + list(fn.args.args) \
        + list(fn.args.kwonlyargs)
    out = {a.arg for a in args
           if any(m in a.arg.lower() for m in DEADLINE_MARKERS)}
    return out


def _kwargs_param(fn: ast.FunctionDef) -> str:
    return fn.args.kwarg.arg if fn.args.kwarg is not None else ""


def _derived_names(fn: ast.FunctionDef, seeds: Set[str]) -> Set[str]:
    """Locals assigned FROM a deadline param (``tmo = timeout_ms or
    self.default``) carry the deadline onward — one level is enough for
    the stack's idioms."""
    out = set(seeds)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        rhs_names = {n.id for n in ast.walk(node.value)
                     if isinstance(n, ast.Name)}
        if rhs_names & seeds:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


class DeadlinePropagationChecker(Checker):
    rule = "deadline-propagation"
    description = ("submit-shaped forwarding calls must thread the "
                   "caller's deadline/timeout parameter instead of "
                   "dropping it")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            for qual, fn, _cls in iter_functions(sf.tree):
                params = _deadline_params(fn)
                if not params:
                    continue
                carriers = _derived_names(fn, params)
                kwargs_name = _kwargs_param(fn)
                for node in scoped_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = call_name(node)
                    if chain is None:
                        continue
                    last = chain.rsplit(".", 1)[-1]
                    if last not in FORWARD_CALLEES:
                        continue
                    if self._call_threads_deadline(node, carriers,
                                                   kwargs_name):
                        continue
                    yield unit.finding(
                        sf, self.rule, node,
                        f"{qual} accepts {'/'.join(sorted(params))} but "
                        f"this {last}() forwards without it — the "
                        f"callee waits unbounded while the caller's "
                        f"deadline expires unenforced; thread the "
                        f"parameter (or shed before forwarding)")

    @staticmethod
    def _call_threads_deadline(call: ast.Call, carriers: Set[str],
                               kwargs_name: str) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(arg):
                if isinstance(n, ast.Name) and n.id in carriers:
                    return True
                if isinstance(n, ast.Attribute) and any(
                        m in n.attr.lower() for m in DEADLINE_MARKERS):
                    # req.deadline_t / self.default_timeout_ms style:
                    # the deadline rides an attribute through the call
                    return True
        # a deadline-named keyword fed from anything (e.g. a recomputed
        # remaining-budget expression) counts as threading
        for kw in call.keywords:
            if kw.arg is not None and any(m in kw.arg.lower()
                                          for m in DEADLINE_MARKERS):
                return True
            if kw.arg is None and isinstance(kw.value, ast.Name) \
                    and kw.value.id == kwargs_name:
                return True
        return False
