"""Analyzer core: source loading, suppression comments, the baseline
file, the checker registry, and the runner.

Design constraints (ISSUE 8):

- **stdlib only** — ``ast`` + ``tokenize``-free comment parsing (a line
  regex); the suite must import in any environment the repo's tests run
  in, including ones without jax on the path (the checkers never import
  the code they analyze — everything is syntactic).
- **fast** — one parse per file, every checker walks the shared ASTs;
  the tier-1 gate asserts < 10 s over ``serving/`` + ``models/``.
- **suppressable, two ways** — an inline ``# analysis: ok <rule> — why``
  comment on the finding line (or the line directly above it) silences
  one site forever; the checked-in baseline file grandfathers a set of
  known findings by content fingerprint (rule + file + enclosing
  function + normalized line text), so findings move with their code
  instead of pinning line numbers.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# inline suppression:  # analysis: ok rule-a rule-b — justification
# separator before the justification is an em/en dash, "--" or ":" (a
# single "-" would be ambiguous with the hyphens in rule names);
# "*" suppresses every rule at the site.
_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ok\s+([\w*,\- ]+?)(?:\s*(?:—|–|--|:)\s*(.*))?\s*$")


def _path_key(path: str) -> str:
    """'serving/generation.py'-style key: parent dir + basename, stable
    across absolute vs repo-relative invocations of the same tree."""
    norm = os.path.normpath(path)
    return os.path.join(os.path.basename(os.path.dirname(norm)),
                        os.path.basename(norm))


@dataclass
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str                      # as given to the analyzer
    line: int
    col: int
    message: str
    func: str = "<module>"         # enclosing function qualname
    line_text: str = ""
    suppressed: bool = False
    suppression: str = ""          # "inline" | "baseline" | ""
    why: str = ""                  # justification carried by the suppression

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def fingerprint(self) -> str:
        """Content fingerprint, stable under line drift: the rule, the
        file (parent dir + basename — a bare basename would collide
        across same-named files like two ``engine.py``, letting one
        file's waiver suppress a brand-new instance elsewhere; the full
        path would break between absolute and relative invocations of
        the same tree), the enclosing function, and the normalized
        source line. Deliberately excludes line/col so a baseline entry
        follows its code through unrelated edits above it."""
        norm = " ".join(self.line_text.split())
        key = "\x1f".join((self.rule, _path_key(self.path),
                           self.func, norm))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "func": self.func,
                "line_text": self.line_text, "suppressed": self.suppressed,
                "suppression": self.suppression, "why": self.why,
                "fingerprint": self.fingerprint()}


class SourceFile:
    """One parsed source file: AST + raw lines + suppression map."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> (set of rules or {"*"}, justification)
        self.suppressions: Dict[int, Tuple[set, str]] = {}
        self._parse_suppressions()
        self._func_of_line = _function_index(self.tree)

    def _parse_suppressions(self):
        for i, raw in enumerate(self.lines, start=1):
            if "analysis:" not in raw:
                continue
            m = _SUPPRESS_RE.search(raw)
            if m is None:
                continue
            rules = {r.strip() for r in re.split(r"[,\s]+", m.group(1))
                     if r.strip()}
            why = (m.group(2) or "").strip()
            self.suppressions[i] = (rules, why)

    def suppression_for(self, line: int, rule: str) -> Optional[str]:
        """The justification string when ``rule`` is suppressed at
        ``line``: an inline comment on the line itself, or anywhere in
        the contiguous comment block directly above it (multi-line
        justifications are encouraged); None otherwise."""
        candidates = [line]
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            entry = self.suppressions.get(ln)
            if entry is None:
                continue
            rules, why = entry
            if rule in rules or "*" in rules:
                return why or "(no reason given)"
        return None

    def func_at(self, line: int) -> str:
        return self._func_of_line.get(line, "<module>")

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def _function_index(tree: ast.AST) -> Dict[int, str]:
    """line -> qualname of the innermost enclosing function/method."""
    index: Dict[int, str] = {}

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = stack + [child.name]
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(name)
                    end = getattr(child, "end_lineno", child.lineno)
                    for ln in range(child.lineno, end + 1):
                        # innermost wins: later (nested) writes overwrite
                        index[ln] = qual
                visit(child, name)
            else:
                visit(child, stack)

    visit(tree, [])
    return index


class AnalysisUnit:
    """Every file of one analyzer run — checkers that need whole-package
    context (taxonomy) see all files at once."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        self.errors: List[str] = []

    def finding(self, sf: SourceFile, rule: str, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=sf.path, line=line, col=col,
                       message=message, func=sf.func_at(line),
                       line_text=sf.line_text(line))


class Checker:
    """Base checker: subclasses set ``rule``/``description`` and yield
    Findings from :meth:`check`."""

    rule = "base"
    description = ""

    def check(self, unit: AnalysisUnit) -> Iterable[Finding]:
        raise NotImplementedError


class Baseline:
    """Checked-in set of grandfathered findings, by fingerprint. The
    file is a JSON list of entries (rule/file/func/line_text/why +
    fingerprint) so reviewers can read WHAT was waived and why — the
    analyzer matches on fingerprint only, and each entry waives ONE
    occurrence (a second identical line appearing in the same function
    later is a NEW finding, not covered by the old waiver)."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        self._by_fp = {e["fingerprint"]: e for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def matcher(self) -> "_BaselineMatcher":
        """A fresh occurrence-counting matcher for one analyzer run."""
        return _BaselineMatcher(self)

    @staticmethod
    def write(path: str, findings: Sequence[Finding],
              why: str = "baselined", loaded: "Optional[Baseline]" = None,
              prune: bool = False) -> int:
        """Grandfather every unsuppressed finding into ``path`` —
        MERGING with the findings the loaded baseline already waives
        (their hand-written ``why`` justifications ride along via
        ``Finding.why``), so re-running ``--write-baseline`` is
        idempotent rather than destructive. ``loaded`` entries that did
        NOT fire in this run are kept too (a run narrowed by --rules or
        a path subset must not garbage-collect out-of-scope waivers);
        pass ``prune=True`` from a FULL-scope run to drop stale entries
        whose code was fixed. Returns the number written."""
        entries = []
        seen = set()
        for f in findings:
            if f.suppressed and f.suppression != "baseline":
                continue   # inline suppressions live in the source
            fp = f.fingerprint()
            seen.add(fp)
            entries.append({
                "rule": f.rule, "file": _path_key(f.path),
                "func": f.func, "line_text": " ".join(f.line_text.split()),
                "why": f.why or why, "fingerprint": fp})
        if loaded is not None and not prune:
            entries.extend(e for e in loaded.entries
                           if e["fingerprint"] not in seen)
        payload = {"comment": "static-analysis baseline: grandfathered "
                              "findings by content fingerprint; prefer "
                              "inline '# analysis: ok <rule> -- why' "
                              "suppressions for new waivers",
                   "findings": entries}
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        return len(entries)


class _BaselineMatcher:
    """Per-run matcher: N entries with one fingerprint waive exactly N
    occurrences. Without the count, the baseline's waiver for one
    ``fut.set_exception(e)`` line would silently suppress every future
    duplicate of that line in the same function — the exact defect
    class the checker exists to block, defeated at its one waived
    site."""

    def __init__(self, baseline: Baseline):
        self._by_fp = baseline._by_fp
        self._avail: Dict[str, int] = {}
        for e in baseline.entries:
            fp = e["fingerprint"]
            self._avail[fp] = self._avail.get(fp, 0) + 1

    def take(self, finding: Finding) -> Optional[dict]:
        fp = finding.fingerprint()
        if self._avail.get(fp, 0) > 0:
            self._avail[fp] -= 1
            return self._by_fp[fp]
        return None


@dataclass
class Report:
    """One analyzer run's outcome."""

    findings: List[Finding] = field(default_factory=list)
    files_analyzed: int = 0
    elapsed_s: float = 0.0
    errors: List[str] = field(default_factory=list)
    rules: Tuple[str, ...] = ()

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def exit_code(self) -> int:
        return 1 if self.unsuppressed or self.errors else 0

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.unsuppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    #: --json report schema version. v2 (ISSUE 11): added this field
    #: itself plus the four cluster-era rules; consumers that pinned the
    #: v1 key set keep working — the schema only grows.
    SCHEMA_VERSION = 2

    def to_dict(self) -> dict:
        return {"schema_version": self.SCHEMA_VERSION,
                "files_analyzed": self.files_analyzed,
                "elapsed_s": round(self.elapsed_s, 4),
                "rules": list(self.rules),
                "counts": {"total": len(self.findings),
                           "unsuppressed": len(self.unsuppressed),
                           "suppressed": len(self.suppressed),
                           "by_rule": self.by_rule()},
                "errors": list(self.errors),
                "findings": [f.to_dict() for f in self.findings]}


def all_checkers() -> List[Checker]:
    """The registered checker set, instantiated fresh (checkers are
    stateless between runs but cheap to build)."""
    from tools.analysis.deadline import DeadlinePropagationChecker
    from tools.analysis.donation import DonationSafetyChecker
    from tools.analysis.exception_chaining import ExceptionChainingChecker
    from tools.analysis.lock_discipline import LockDisciplineChecker
    from tools.analysis.metrics_drift import MetricsDriftChecker
    from tools.analysis.recompile import RecompileRiskChecker
    from tools.analysis.taxonomy import TaxonomyDriftChecker
    from tools.analysis.terminal import TerminalExactlyOnceChecker
    from tools.analysis.wire_schema import WireSchemaDriftChecker

    return [LockDisciplineChecker(), DonationSafetyChecker(),
            TaxonomyDriftChecker(), TerminalExactlyOnceChecker(),
            RecompileRiskChecker(), WireSchemaDriftChecker(),
            DeadlinePropagationChecker(), MetricsDriftChecker(),
            ExceptionChainingChecker()]


def _collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[Sequence[str]] = None,
                    baseline: Optional[Baseline] = None) -> Report:
    """Analyze in-memory sources ({path: text}) — the runner the CLI,
    the tests, and the fixture snippets all share."""
    t0 = time.perf_counter()
    files: List[SourceFile] = []
    errors: List[str] = []
    for path, text in sources.items():
        try:
            files.append(SourceFile(path, text))
        except SyntaxError as e:
            errors.append(f"{path}: syntax error: {e.msg} (line {e.lineno})")
    unit = AnalysisUnit(files)
    checkers = [c for c in all_checkers()
                if rules is None or c.rule in rules]
    findings: List[Finding] = []
    for checker in checkers:
        findings.extend(checker.check(unit))
    by_path = {sf.path: sf for sf in files}
    matcher = baseline.matcher() if baseline is not None else None
    for f in findings:
        sf = by_path.get(f.path)
        why = sf.suppression_for(f.line, f.rule) if sf is not None else None
        if why is not None:
            f.suppressed, f.suppression, f.why = True, "inline", why
            continue
        if matcher is not None:
            entry = matcher.take(f)
            if entry is not None:
                f.suppressed = True
                f.suppression = "baseline"
                f.why = entry.get("why", "")
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(findings=findings, files_analyzed=len(files),
                  elapsed_s=time.perf_counter() - t0, errors=errors,
                  rules=tuple(c.rule for c in checkers))


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence[str]] = None,
                  baseline: Optional[Baseline] = None) -> Report:
    """Analyze files/directories on disk."""
    sources: Dict[str, str] = {}
    errors: List[str] = []
    for fp in _collect_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                sources[fp] = f.read()
        except OSError as e:
            errors.append(f"{fp}: {e}")
    report = analyze_sources(sources, rules=rules, baseline=baseline)
    report.errors = errors + report.errors
    return report


# ---------------------------------------------------------------- AST utils
def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for a Name/Attribute chain ('self._cache',
    'np.zeros'), or None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted callee name of a Call, or None."""
    return attr_chain(node.func)


def iter_functions(tree: ast.AST):
    """Yield (qualname, FunctionDef, enclosing ClassDef-or-None) for
    every function/method, including nested ones."""
    def visit(node, stack, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, stack + [child.name], child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield ".".join(stack + [child.name]), child, cls
                yield from visit(child, stack + [child.name], cls)
            else:
                yield from visit(child, stack, cls)

    yield from visit(tree, [], None)


def string_value(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def scoped_walk(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested defs — those
    are separate scopes, yielded separately by :func:`iter_functions`,
    and per-function checkers that used a plain ``ast.walk`` would both
    double-report nested sites and bleed scope facts across the
    boundary."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
