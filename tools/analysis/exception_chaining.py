"""``exception-chaining``: don't lose the cause inside ``except``.

The taxonomy (``tracing.terminal_reason``) and the crash dumps
(``util/crash_reporting``) both walk ``__cause__`` chains to answer
"WHY did this request fail" — a ``raise NewError(...)`` inside an
``except`` block without ``from`` replaces the explicit cause chain
with implicit ``__context__``, which ``raise ... from None``-style
sanitizing, future refactors, and the dump renderer all treat
differently. PR 10's bounce-retry conversion
(``ClusterCapacityError(...) from host_rejection``) is the idiom: the
fleet-level shed CARRIES the host's typed rejection.

The rule: a ``raise <Constructor>(...)`` lexically inside an ``except``
handler must carry an explicit ``from`` clause — ``from e`` to chain,
``from None`` to deliberately sever. Bare ``raise`` (re-raise) and
``raise e`` (the caught object itself) keep their tracebacks and are
exempt, as are raises inside nested ``def``\\ s (those run later,
outside the handler's context).
"""
from __future__ import annotations

import ast

from tools.analysis.core import AnalysisUnit, Checker


def _handler_raises(handler: ast.ExceptHandler):
    """Raise nodes lexically inside this handler's body, nested
    defs/handlers excluded (inner handlers are visited on their own)."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ExceptHandler)):
            continue
        if isinstance(node, ast.Raise):
            yield node
        stack.extend(ast.iter_child_nodes(node))


class ExceptionChainingChecker(Checker):
    rule = "exception-chaining"
    description = ("raise <NewError>(...) inside an except block without "
                   "'from' loses the cause the taxonomy and crash dumps "
                   "depend on")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                for r in _handler_raises(node):
                    if r.exc is None or r.cause is not None:
                        continue   # bare re-raise / explicit from
                    if not isinstance(r.exc, ast.Call):
                        continue   # `raise e` keeps its traceback
                    yield unit.finding(
                        sf, self.rule, r,
                        f"raise inside an except block without 'from' — "
                        f"the cause chain the taxonomy and crash dumps "
                        f"walk is lost; write 'raise ... from "
                        f"{node.name or 'e'}' (or 'from None' to sever "
                        f"deliberately)")
