"""``donation-safety``: use-after-donate on the donated KV cache.

The generation path compiles its prefill/decode executables with
``donate_argnums`` on the cache argument: the instant the call
dispatches, the caller's binding may refer to CONSUMED buffers. The bug
class this checker encodes is exactly what PR 3 and PR 6 fixed by
review: a thread reads the donated binding *after* the donated call —
re-dispatching it, freeing blocks against it, or re-reading
``self._cache`` after a watchdog restart swapped it — and dies later
with "Array has been deleted" (or worse, consumes the replacement
scheduler's live buffers).

The rule, per function scope (nested ``def``\\ s are separate scopes —
the retry closures deliberately re-read ``self._cache`` per attempt,
which is safe because tagged-transient faults raise BEFORE dispatch):

    after a statement that passes binding X to a donated callable,
    any later READ of X in the same scope is a finding, unless
    (a) X was re-assigned first (the rebuild/writeback pattern:
    ``self._cache = new_cache`` / ``self._reset_cache()``), or
    (b) the read sits under an epoch/zombie guard (an ``if``/``while``
    whose test mentions ``epoch`` or ``current`` — the stale-thread
    check every writeback uses).

Donated callables are recognized syntactically: ``self._prefill`` /
``self._decode`` (the engine's two executables), anything routed
through ``self._donated_call(point, fn, *args)``, and any callee whose
name ends with ``_donated``. Donated bindings are the cache-like
arguments: ``self._cache`` or any name/attribute whose final segment
contains ``cache``.

ISSUE 11 added BOUNDED TRANSITIVE same-class call expansion: a method
that donates ``self._cache`` (directly, through a retry closure, or
through further same-class calls up to :data:`EXPANSION_DEPTH` levels)
and never rebinds it afterwards leaves the binding consumed for its
CALLER — so ``self._step_once()`` acts as a donation event in the
calling scope, and a read of ``self._cache`` after it (with no rebind
or epoch guard) is the same use-after-donate the direct form is. A
method that writes ``self._cache`` back anywhere (the epoch-guarded
writeback every scheduler path uses) does NOT propagate — its callers
see a live binding.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, iter_functions,
)

DONATED_CALLEES = {"_prefill", "_decode"}

#: Same-class call levels the consumed-binding summary propagates
#: through (mirrors lock_discipline.EXPANSION_DEPTH).
EXPANSION_DEPTH = 4


def _is_donated_call(node: ast.Call) -> bool:
    chain = call_name(node)
    if chain is None:
        return False
    last = chain.rsplit(".", 1)[-1]
    return (last in DONATED_CALLEES or last == "_donated_call"
            or last.endswith("_donated"))


def _donated_args(node: ast.Call) -> List[str]:
    """Cache-like bindings this donated call consumes."""
    out = []
    for arg in node.args:
        chain = attr_chain(arg)
        if chain is None:
            continue
        if "cache" in chain.rsplit(".", 1)[-1].lower():
            out.append(chain)
    return out


def _reads_and_writes(node: ast.AST, scope_end: int):
    """Every (chain, lineno, col, is_store, node) reference in this
    scope, nested function bodies excluded."""
    refs = []

    def walk(n):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            chain = attr_chain(child)
            if chain is not None and isinstance(child,
                                                (ast.Name, ast.Attribute)):
                is_store = isinstance(getattr(child, "ctx", None),
                                      (ast.Store, ast.Del))
                refs.append((chain, child.lineno, child.col_offset,
                             is_store, child))
                # don't descend into an Attribute chain's pieces
                continue
            walk(child)

    walk(node)
    return refs


def _guard_lines(fn: ast.AST) -> Set[int]:
    """Lines covered by an epoch/zombie guard: the body of any if/while
    whose test mentions an identifier containing 'epoch' or 'current'
    (plus the writeback idiom ``if current: self._cache = ...``)."""
    guarded: Set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        test_ids = {n.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Name)}
        test_ids |= {n.attr for n in ast.walk(node.test)
                     if isinstance(n, ast.Attribute)}
        if any("epoch" in i or "current" in i for i in test_ids):
            end = getattr(node, "end_lineno", node.lineno)
            guarded.update(range(node.lineno, end + 1))
    return guarded


def _method_summary(fn: ast.FunctionDef) -> Tuple[Set[str], Set[str]]:
    """(self-attr bindings this method donates ANYWHERE — including
    inside its retry closures, which run before the method returns —
    and self-attr bindings it stores). A method whose donated set minus
    its stored set is non-empty leaves those bindings consumed for its
    caller."""
    donated: Set[str] = set()
    stored: Set[str] = set()
    aliases: Dict[str, str] = {}   # local name -> self.* source
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            src = attr_chain(node.value)
            if src is not None and src.startswith("self.") \
                    and "cache" in src.rsplit(".", 1)[-1].lower():
                for tgt in node.targets:
                    t = attr_chain(tgt)
                    if t is not None and not t.startswith("self."):
                        aliases[t] = src
        if isinstance(node, ast.Call) and _is_donated_call(node):
            for b in _donated_args(node):
                if b.startswith("self."):
                    donated.add(b)
                elif b in aliases:
                    donated.add(aliases[b])
        chain = attr_chain(node)
        if chain is not None and chain.startswith("self.") \
                and isinstance(getattr(node, "ctx", None), ast.Store):
            stored.add(chain)
    return donated, stored


def _class_consumers(methods: Dict[str, ast.FunctionDef],
                     depth: int = EXPANSION_DEPTH) -> Dict[str, Set[str]]:
    """Per method: the self-attr bindings a call to it leaves consumed,
    propagated through same-class calls up to ``depth`` levels."""
    direct: Dict[str, Set[str]] = {}
    stores: Dict[str, Set[str]] = {}
    callees: Dict[str, Set[str]] = {}
    for name, fn in methods.items():
        donated, stored = _method_summary(fn)
        direct[name] = donated
        stores[name] = stored
        calls = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = call_name(node)
                if chain is not None and chain.startswith("self.") \
                        and chain.count(".") == 1:
                    calls.add(chain.split(".", 1)[1])
        callees[name] = calls & set(methods)
    summary = {name: direct[name] - stores[name] for name in methods}
    for _ in range(max(0, depth - 1)):
        changed = False
        for name in methods:
            inherited: Set[str] = set()
            for callee in callees[name]:
                inherited |= summary.get(callee, set())
            new = (direct[name] | inherited) - stores[name]
            if new != summary[name]:
                summary[name] = new
                changed = True
        if not changed:
            break
    return {name: s for name, s in summary.items() if s}


class DonationSafetyChecker(Checker):
    rule = "donation-safety"
    description = ("reads of a donated cache binding after the donated "
                   "call (direct, or through a same-class method that "
                   "leaves the binding consumed), with no rebuild/epoch "
                   "guard in between")

    def __init__(self, expansion_depth: int = EXPANSION_DEPTH):
        self.expansion_depth = expansion_depth

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            # per-class consumed-binding summaries for the transitive
            # expansion (same-file classes only: the donation chains all
            # live inside one engine module)
            consumers_by_class: Dict[str, Dict[str, Set[str]]] = {}
            methods_by_class: Dict[str, Dict[str, ast.FunctionDef]] = {}
            for qual, fn, cls in iter_functions(sf.tree):
                if cls is not None and "." not in qual[:-len(fn.name) - 1]:
                    methods_by_class.setdefault(cls.name, {})
                    if fn.name not in methods_by_class[cls.name]:
                        methods_by_class[cls.name][fn.name] = fn
            for cname, methods in methods_by_class.items():
                consumers_by_class[cname] = _class_consumers(
                    methods, self.expansion_depth)
            for qual, fn, cls in iter_functions(sf.tree):
                consumers = consumers_by_class.get(
                    cls.name, {}) if cls is not None else {}
                # a method must not treat its OWN call chain as a
                # donation event for itself (recursion)
                consumers = {k: v for k, v in consumers.items()
                             if k != fn.name}
                yield from self._check_function(unit, sf, fn, consumers)

    def _check_function(self, unit, sf, fn, consumers=None):
        # donation events in THIS scope (nested defs excluded). A donated
        # call whose enclosing statement is a return/raise leaves the
        # scope on that path — nothing can read the binding "after" it
        # (the engines' retry closures end in exactly this shape).
        donations: List[Tuple[ast.Call, List[str], Optional[ast.stmt]]] = []

        def find_calls(n, stmt):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                child_stmt = child if isinstance(child, ast.stmt) else stmt
                if isinstance(child, ast.Call) \
                        and not isinstance(child_stmt,
                                           (ast.Return, ast.Raise)):
                    if _is_donated_call(child):
                        args = _donated_args(child)
                        if args:
                            donations.append((child, args, child_stmt))
                    elif consumers:
                        # transitive: a same-class method that leaves
                        # self-attr bindings consumed is a donation
                        # event in this scope too
                        chain = call_name(child)
                        if chain is not None and chain.startswith("self.") \
                                and chain.count(".") == 1:
                            m = chain.split(".", 1)[1]
                            if m in consumers:
                                donations.append(
                                    (child, sorted(consumers[m]),
                                     child_stmt))
                find_calls(child, child_stmt)

        find_calls(fn, None)
        if not donations:
            return
        refs = _reads_and_writes(fn, getattr(fn, "end_lineno", fn.lineno))
        # within one line, Loads sort BEFORE Stores (False < True):
        # Python evaluates an assignment's RHS before binding its
        # target, so in ``self._cache = trim(self._cache)`` the read of
        # the consumed buffers happens first and must be visited before
        # the Store marks the binding rebound
        refs.sort(key=lambda r: (r[1], r[3], r[2]))
        guarded = _guard_lines(fn)
        end = getattr(fn, "end_lineno", fn.lineno)
        for call, bindings, stmt in donations:
            call_end = getattr(call, "end_lineno", call.lineno)
            # taint the alias AND (for a local snapshot like
            # ``cache = self._cache``) its source attribute: after the
            # snapshot is donated, both names refer to consumed buffers
            tainted = set(bindings)
            tainted |= self._alias_sources(fn, bindings, call.lineno)
            # the donation's own assignment targets are rebinds — the
            # canonical same-line writeback (``self._cache, toks =
            # self._decode(..., self._cache, ...)``) leaves the binding
            # holding the FRESH cache
            rebound: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    for el in ast.walk(tgt):
                        c = attr_chain(el)
                        if c is not None:
                            rebound.add(c)
            for chain, ln, col, is_store, node in refs:
                if ln <= call_end:
                    continue
                if chain not in tainted or chain in rebound:
                    continue
                if is_store:
                    rebound.add(chain)
                    continue
                if ln in guarded:
                    continue
                yield unit.finding(
                    sf, self.rule, node,
                    f"read of {chain} after it was donated to "
                    f"{call_name(call)}() at line {call.lineno} with no "
                    f"rebind or epoch guard between them — the buffers "
                    f"may be consumed (use-after-donate; rebuild via "
                    f"_reset_cache / re-assign before reading)")

    @staticmethod
    def _alias_sources(fn, bindings, before_line) -> Set[str]:
        """For a donated local alias (``cache = self._cache`` above the
        donation), the source attribute is tainted too."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or node.lineno >= before_line:
                continue
            src = attr_chain(node.value)
            if src is None or "cache" not in src.rsplit(".", 1)[-1].lower():
                continue
            for tgt in node.targets:
                t = attr_chain(tgt)
                if t in bindings:
                    out.add(src)
        return out
