"""CLI: ``python -m tools.analysis <paths...> [options]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings (or file
errors), 2 = usage error. ``--json`` emits the machine-readable report
(bench/CI parse it); the default human output is one
``path:line:col: rule: message`` line per finding plus a summary.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.analysis.core import (
    Baseline, _collect_files, all_checkers, analyze_paths,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def changed_files(base_ref: str, paths=None, cwd=None):
    """.py files changed vs ``base_ref`` (committed, working-tree, AND
    untracked changes — the pre-commit view; ``git diff`` alone never
    lists a brand-new un-added file, which would make the mode a false
    green on exactly the files most likely to carry fresh findings),
    optionally intersected with ``paths``. Raises RuntimeError when git
    can't answer (not a repo, unknown ref) so the CLI can exit 2
    instead of a false green."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", base_ref, "--"],
            capture_output=True, text=True, cwd=cwd, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard",
             "--full-name"],
            capture_output=True, text=True, cwd=cwd, timeout=30)
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, cwd=cwd, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"git diff failed: {e}") from e
    if out.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base_ref} failed: "
            f"{out.stderr.strip() or out.stdout.strip()}")
    if untracked.returncode != 0:
        raise RuntimeError(
            f"git ls-files --others failed: {untracked.stderr.strip()}")
    if top.returncode != 0:
        raise RuntimeError(
            f"git rev-parse --show-toplevel failed: {top.stderr.strip()}")
    # git prints repo-root-relative paths; resolving them against the
    # CWD would silently drop every file when run from a subdirectory
    # (a pre-commit gate that exits 0 on a typo'd invocation)
    root = top.stdout.strip()
    files = []
    for rel in dict.fromkeys(out.stdout.splitlines()
                             + untracked.stdout.splitlines()):
        rel = rel.strip()
        if not rel.endswith(".py"):
            continue
        fp = os.path.join(root, rel)
        if not os.path.exists(fp):
            continue   # deleted files have nothing to analyze
        if paths:
            norm = os.path.normpath(os.path.abspath(fp))
            keep = False
            for p in paths:
                pn = os.path.normpath(os.path.abspath(p))
                if norm == pn or norm.startswith(pn + os.sep):
                    keep = True
                    break
            if not keep:
                continue
        files.append(fp)
    return sorted(files)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="Repo-specific static analysis for the serving "
                    "stack's concurrency/donation/taxonomy contracts.")
    p.add_argument("paths", nargs="*",
                   help="files or directories to analyze (optional with "
                        "--changed-only, where they narrow the diff)")
    p.add_argument("--json", action="store_true",
                   help="emit the JSON report instead of human output")
    p.add_argument("--changed-only", action="store_true",
                   help="analyze only .py files in the git diff vs "
                        "--base-ref (fast pre-commit mode; no changed "
                        "files = clean exit 0)")
    p.add_argument("--base-ref", default="HEAD",
                   help="base ref for --changed-only (default: HEAD — "
                        "staged + unstaged changes)")
    p.add_argument("--rules",
                   help="comma-separated subset of rules to run "
                        f"(default: all — "
                        f"{','.join(c.rule for c in all_checkers())})")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file of grandfathered findings "
                        "(default: tools/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current unsuppressed finding "
                        "into --baseline (merged with existing entries) "
                        "and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="with --write-baseline: drop baseline entries "
                        "whose finding no longer fires — only safe from "
                        "a FULL-scope run (all paths, all rules)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule}: {c.description}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        valid = {c.rule for c in all_checkers()}
        unknown = [r for r in rules if r not in valid]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(valid: {', '.join(sorted(valid))})", file=sys.stderr)
            return 2
    if args.prune_baseline and not args.write_baseline:
        print("--prune-baseline only applies with --write-baseline",
              file=sys.stderr)
        return 2
    if not args.paths and not args.changed_only:
        print("paths are required (or pass --changed-only)",
              file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2
    if args.changed_only:
        if args.write_baseline:
            # a baseline regenerated from a diff-narrowed view would be
            # exactly the partial-view hazard the parse-error guard
            # blocks — refuse outright
            print("--write-baseline needs the full view; drop "
                  "--changed-only", file=sys.stderr)
            return 2
        try:
            targets = changed_files(args.base_ref, args.paths)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        if not targets:
            # the pre-commit fast path: a diff with no .py changes is a
            # clean run, not the no-.py-files usage error explicit
            # paths get — there was nothing to drift
            print(f"no .py files changed vs {args.base_ref}: clean")
            return 0
    else:
        # a path that exists but contributes no .py files is a usage
        # error, not a clean run: a typo'd/renamed directory in a CI
        # invocation must not turn the gate into a permanent false green
        empty = [p for p in args.paths if not _collect_files([p])]
        if empty:
            print(f"no .py files under: {', '.join(empty)}",
                  file=sys.stderr)
            return 2
        targets = args.paths
    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    report = analyze_paths(targets, rules=rules, baseline=baseline)

    if args.write_baseline:
        if report.errors:
            # refuse to regenerate from a partial view: a file that
            # failed to parse would silently drop its waived findings
            # from the baseline, and CI would fail once it parses again
            for err in report.errors:
                print(f"ERROR: {err}", file=sys.stderr)
            print("baseline NOT written (fix the errors above first)",
                  file=sys.stderr)
            return 1
        n = Baseline.write(args.baseline, report.findings,
                           loaded=baseline, prune=args.prune_baseline)
        print(f"baselined {n} finding(s) -> {args.baseline}")
        return 0

    if args.json:
        json.dump(report.to_dict(), sys.stdout, indent=2)
        print()
        return report.exit_code

    for err in report.errors:
        print(f"ERROR: {err}")
    for f in report.unsuppressed:
        print(f"{f.location()}: {f.rule}: {f.message}")
    n_un, n_sup = len(report.unsuppressed), len(report.suppressed)
    by_rule = ", ".join(f"{r}={n}" for r, n in sorted(report.by_rule().items()))
    print(f"\n{report.files_analyzed} file(s) analyzed in "
          f"{report.elapsed_s * 1e3:.0f} ms: {n_un} finding(s)"
          + (f" ({by_rule})" if by_rule else "")
          + (f", {n_sup} suppressed" if n_sup else ""))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
