"""CLI: ``python -m tools.analysis <paths...> [options]``.

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings (or file
errors), 2 = usage error. ``--json`` emits the machine-readable report
(bench/CI parse it); the default human output is one
``path:line:col: rule: message`` line per finding plus a summary.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from tools.analysis.core import (
    Baseline, _collect_files, all_checkers, analyze_paths,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="Repo-specific static analysis for the serving "
                    "stack's concurrency/donation/taxonomy contracts.")
    p.add_argument("paths", nargs="+",
                   help="files or directories to analyze")
    p.add_argument("--json", action="store_true",
                   help="emit the JSON report instead of human output")
    p.add_argument("--rules",
                   help="comma-separated subset of rules to run "
                        f"(default: all — "
                        f"{','.join(c.rule for c in all_checkers())})")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file of grandfathered findings "
                        "(default: tools/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current unsuppressed finding "
                        "into --baseline (merged with existing entries) "
                        "and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="with --write-baseline: drop baseline entries "
                        "whose finding no longer fires — only safe from "
                        "a FULL-scope run (all paths, all rules)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_rules:
        for c in all_checkers():
            print(f"{c.rule}: {c.description}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        valid = {c.rule for c in all_checkers()}
        unknown = [r for r in rules if r not in valid]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(valid: {', '.join(sorted(valid))})", file=sys.stderr)
            return 2
    if args.prune_baseline and not args.write_baseline:
        print("--prune-baseline only applies with --write-baseline",
              file=sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2
    # a path that exists but contributes no .py files is a usage error,
    # not a clean run: a typo'd/renamed directory in a CI invocation
    # must not turn the gate into a permanent false green
    empty = [p for p in args.paths if not _collect_files([p])]
    if empty:
        print(f"no .py files under: {', '.join(empty)}", file=sys.stderr)
        return 2
    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    report = analyze_paths(args.paths, rules=rules, baseline=baseline)

    if args.write_baseline:
        if report.errors:
            # refuse to regenerate from a partial view: a file that
            # failed to parse would silently drop its waived findings
            # from the baseline, and CI would fail once it parses again
            for err in report.errors:
                print(f"ERROR: {err}", file=sys.stderr)
            print("baseline NOT written (fix the errors above first)",
                  file=sys.stderr)
            return 1
        n = Baseline.write(args.baseline, report.findings,
                           loaded=baseline, prune=args.prune_baseline)
        print(f"baselined {n} finding(s) -> {args.baseline}")
        return 0

    if args.json:
        json.dump(report.to_dict(), sys.stdout, indent=2)
        print()
        return report.exit_code

    for err in report.errors:
        print(f"ERROR: {err}")
    for f in report.unsuppressed:
        print(f"{f.location()}: {f.rule}: {f.message}")
    n_un, n_sup = len(report.unsuppressed), len(report.suppressed)
    by_rule = ", ".join(f"{r}={n}" for r, n in sorted(report.by_rule().items()))
    print(f"\n{report.files_analyzed} file(s) analyzed in "
          f"{report.elapsed_s * 1e3:.0f} ms: {n_un} finding(s)"
          + (f" ({by_rule})" if by_rule else "")
          + (f", {n_sup} suppressed" if n_sup else ""))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
