"""``lock-discipline``: the serving stack's locking contract, checked
from the AST.

The contract (written down in PR 1/3/7 review rounds, now enforced):

1. **No blocking under a held lock.** ``AdmissionController.take()``
   fails shed futures OUTSIDE its condition lock because
   ``Future.set_exception`` runs done-callbacks synchronously and a
   retry-on-shed callback re-entering the controller would deadlock.
   The same reasoning bans ``future.result()``, ``thread.join()``,
   ``time.sleep()``, and engine dispatch (``_dispatch`` /
   ``_guarded_run`` / ``_donated_call`` / the jitted executables /
   ``inject``) inside any ``with self._lock:`` region. A
   ``Condition.wait`` on the SAME lock is exempt (wait releases it);
   a wait on a different lock while holding one is the classic
   two-lock sleep and is flagged.
2. **No same-lock re-acquisition.** Every lock here is a non-reentrant
   ``threading.Lock``/``Condition`` — ``with self._lock:`` nested
   (lexically, or via a same-class method call) inside a region already
   holding ``self._lock`` is a guaranteed deadlock
   (``register_prefix`` inlines the ``_usable_blocks`` sum for exactly
   this reason).
3. **No lock-order inversions.** Nested acquisitions define edges in a
   per-class lock graph (A held while B is taken => A -> B); a cycle
   means two threads can deadlock. Edges come from lexical nesting plus
   BOUNDED TRANSITIVE same-class call expansion (method f holds A and
   calls ``self.g()``; g calls ``self.h()``; h acquires B — the A -> B
   edge is found through the whole chain, up to
   :data:`EXPANSION_DEPTH` call levels). ISSUE 11 upgraded this from
   one level: the serving stack's real deadlock risks live two and
   three calls deep (``_decode_iteration -> _clear_slot ->
   allocator``-shaped chains), which the one-level expansion was blind
   to. Blocking calls propagate through the same chains: f holding A
   and calling ``self.g()`` where g (or anything g reaches, same
   class) sleeps/joins/dispatches is flagged at f's call site.

Lock sites are recognized syntactically: ``with self.<attr>:`` where
the attribute name contains ``lock`` or ``cv`` (``_lock``, ``_wd_lock``,
``_prefix_lock``, ``_cv``, ...), plus bare local names matching the
same pattern.

:func:`static_lock_graph` exports the same per-class edge set (with
mixin/base-class edges projected onto their subclasses) as a plain
``{"edges": [[outer, inner], ...]}`` graph over ``Class.attr`` nodes —
the static half of the runtime-lockdep differential
(:mod:`tools.analysis.lockdep` records the dynamic half from
instrumented locks while the chaos suite runs).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, iter_functions,
)

#: How many same-class call levels the transitive expansion follows.
#: Depth 1 is the pre-ISSUE-11 behavior (direct callees only); the
#: serving stack's deepest real chain today is 3 calls, so 4 leaves one
#: level of headroom without risking pathological blowup on cyclic call
#: graphs (the walker is visited-set bounded anyway).
EXPANSION_DEPTH = 4

#: Callees that block (or can block) the calling thread. Matched on the
#: FINAL attribute / bare name of the callee.
BLOCKING_ATTRS = {"result", "join"}
#: Dispatch-path callables: a device call under a lock stalls every
#: other thread that needs it for as long as XLA runs (or forever, if
#: the dispatch wedges — the watchdog would then deadlock against the
#: held lock too).
DISPATCH_CALLEES = {
    "_dispatch", "_run", "_guarded_run", "_retry_call", "_donated_call",
    "inject", "_prefill", "_decode", "_prefill_into", "_decode_iteration",
    "_prefill_prefix", "_fwd", "infer",
}
#: Receivers whose .join() is string/path joining, not thread joining.
SAFE_JOIN_RECEIVERS = {"os.path", "posixpath", "ntpath", "path"}


def is_lock_expr(node: ast.AST) -> Optional[str]:
    """The lock key when ``node`` looks like a lock object, else None.
    Keys are the dotted chain ('self._wd_lock'); bare names count too
    ('lock' locals in helpers)."""
    chain = attr_chain(node)
    if chain is None:
        return None
    last = chain.rsplit(".", 1)[-1].lower()
    if "lock" in last or last == "_cv" or last == "cv":
        return chain
    return None


def _with_locks(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        key = is_lock_expr(item.context_expr)
        if key is not None:
            out.append(key)
    return out


class _FunctionLockInfo:
    """Per-function lock facts: every lock the function acquires
    anywhere, and (lock, node, held-set) for each call made while at
    least one lock is held."""

    def __init__(self):
        self.acquires: Set[str] = set()
        # (held locks tuple, Call node) for calls under a lock
        self.calls_under_lock: List[Tuple[Tuple[str, ...], ast.Call]] = []
        # lexical nesting edges: (outer, inner, With node)
        self.nested: List[Tuple[str, str, ast.With]] = []
        # same-lock relock sites: (lock, With node)
        self.relocks: List[Tuple[str, ast.With]] = []
        # self-method calls under a lock: (held, method name, Call node)
        self.self_calls: List[Tuple[Tuple[str, ...], str, ast.Call]] = []
        # every self-method call, held or not — the transitive expansion
        # follows these to find acquires/blocking calls further down
        self.all_self_calls: Set[str] = set()
        # blocking calls ANYWHERE in the function (held or not) as
        # (why, Call node): when a caller holds a lock and reaches this
        # function through same-class calls, these block under that lock
        self.blocking_calls: List[Tuple[str, ast.Call]] = []
        # Condition.wait sites that are exempt LOCALLY (wait on a lock
        # this function itself holds, or a lone wait with nothing held)
        # as (waited-on lock key, Call node): a caller holding a
        # DIFFERENT lock through a same-class call chain turns these
        # into the two-lock sleep — wait releases only its own lock
        self.lock_waits: List[Tuple[str, ast.Call]] = []


def _scan_function(fn: ast.FunctionDef) -> _FunctionLockInfo:
    info = _FunctionLockInfo()

    def walk(node, held: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # nested defs run later, not under this lock
            if isinstance(child, ast.With):
                # multi-item ``with a, b:`` acquires left to right: each
                # item is already held when the next acquires, so the
                # items order-edge (and relock-check) against each other
                # exactly like lexically nested with-statements
                cur = held
                for lk in _with_locks(child):
                    info.acquires.add(lk)
                    for outer in cur:
                        if outer == lk:
                            info.relocks.append((lk, child))
                        else:
                            info.nested.append((outer, lk, child))
                    cur = cur + (lk,)
                walk(child, cur)
                continue
            if isinstance(child, ast.Call):
                chain = call_name(child)
                if held:
                    info.calls_under_lock.append((held, child))
                if chain is not None and chain.startswith("self.") \
                        and chain.count(".") == 1:
                    info.all_self_calls.add(chain.split(".", 1)[1])
                    if held:
                        info.self_calls.append((held, chain.split(".", 1)[1],
                                                child))
                blocking, why = _is_blocking_call(child, held)
                if blocking:
                    info.blocking_calls.append((why, child))
                elif chain is not None and \
                        chain.rsplit(".", 1)[-1] in ("wait", "wait_for") \
                        and isinstance(child.func, ast.Attribute) \
                        and is_lock_expr(child.func.value) is not None:
                    info.lock_waits.append(
                        (attr_chain(child.func.value), child))
            walk(child, held)

    walk(fn, ())
    return info


def _is_blocking_call(call: ast.Call, held: Tuple[str, ...]):
    """(True, why) when this call blocks under a held lock."""
    chain = call_name(call)
    if chain is None:
        return False, ""
    parts = chain.rsplit(".", 1)
    recv = parts[0] if len(parts) == 2 else ""
    last = parts[-1]
    if chain == "time.sleep" or last == "sleep":
        return True, "time.sleep"
    if last in BLOCKING_ATTRS:
        if last == "join" and (recv in SAFE_JOIN_RECEIVERS
                               or recv.endswith("path")):
            return False, ""
        # str.join on a literal separator: ", ".join(...)
        if last == "join" and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Constant):
            return False, ""
        return True, f".{last}()"
    if last in ("wait", "wait_for"):
        # Condition.wait on a HELD lock releases it while waiting — the
        # canonical pattern; waiting on anything else under a lock is
        # a two-lock sleep (with nothing held, a lone wait is not this
        # checker's business)
        if recv in held:
            return False, ""
        if held and is_lock_expr(call.func.value if isinstance(call.func,
                                                               ast.Attribute)
                                 else call.func) is not None:
            return True, f"wait on {recv or chain} while holding a " \
                         f"different lock"
        return False, ""
    if last in DISPATCH_CALLEES:
        return True, f"engine dispatch via {chain}()"
    if last == "get" and recv and ("queue" in recv.lower()
                                   or recv.endswith("_q")):
        return True, f"queue get on {recv}"
    return False, ""


def _reachable_facts(fns: Dict[str, _FunctionLockInfo], root: str,
                     depth: int):
    """Locks acquired, blocking calls, and locally-exempt Condition
    waits reachable from same-class method ``root`` within ``depth``
    call levels (``root``'s own body is level 1). Returns
    ``({lock: call path}, [(why, call path)], {waited lock: call path})``
    or None when ``root`` is not a method of this class; paths are
    tuples of method names starting at ``root``. Visited-set bounded,
    so a recursive call graph terminates regardless of depth."""
    if root not in fns:
        return None
    acquires: Dict[str, Tuple[str, ...]] = {}
    blocking: List[Tuple[str, Tuple[str, ...]]] = []
    blocked_seen: Set[Tuple[str, Tuple[str, ...]]] = set()
    waits: Dict[str, Tuple[str, ...]] = {}
    seen = {root}
    frontier: List[Tuple[str, Tuple[str, ...]]] = [(root, (root,))]
    level = 0
    while frontier and level < depth:
        level += 1
        nxt: List[Tuple[str, Tuple[str, ...]]] = []
        for fname, path in frontier:
            info = fns[fname]
            for lk in sorted(info.acquires):
                acquires.setdefault(lk, path)
            for why, _node in info.blocking_calls:
                if (why, path) not in blocked_seen:
                    blocked_seen.add((why, path))
                    blocking.append((why, path))
            for wlk, _node in info.lock_waits:
                waits.setdefault(wlk, path)
            for callee in sorted(info.all_self_calls):
                if callee in fns and callee not in seen:
                    seen.add(callee)
                    nxt.append((callee, path + (callee,)))
        frontier = nxt
    return acquires, blocking, waits


class _ClassIndex:
    """Unit-wide class resolution: per-class function infos with
    ancestor methods folded in (subclass methods shadow), so the
    transitive expansion follows ``self._retry_call()`` from an engine
    method into the mixin that defines it — class hierarchies span
    files in the serving stack (ResilientEngineMixin lives in
    resilience.py, its subclasses in engine.py/generation.py)."""

    def __init__(self, unit: AnalysisUnit):
        # classes are keyed (file path, class name): two unrelated
        # same-named classes in different files must NOT merge into one
        # lock graph — merged edges fabricate inversions spanning
        # classes that never share an instance, and transitive
        # expansion would follow the wrong class's methods. Base-name
        # resolution (the deliberate cross-file mixin case) goes
        # through _resolve below.
        self.fns_raw: Dict[Tuple[str, str],
                           Dict[str, _FunctionLockInfo]] = {}
        self.bases: Dict[Tuple[str, str], List[str]] = {}
        self.by_name: Dict[str, List[Tuple[str, str]]] = {}
        # (sf, qual, cls, info) for every function, for per-site checks
        self.all_fns: List[Tuple[object, str, Optional[ast.ClassDef],
                                 ast.FunctionDef, _FunctionLockInfo]] = []
        for sf in unit.files:
            for qual, fn, cls in iter_functions(sf.tree):
                info = _scan_function(fn)
                self.all_fns.append((sf, qual, cls, fn, info))
                if cls is None:
                    continue
                key = (sf.path, cls.name)
                if key not in self.fns_raw:
                    self.fns_raw[key] = {}
                    self.by_name.setdefault(cls.name, []).append(key)
                    self.bases[key] = [
                        b.id if isinstance(b, ast.Name) else b.attr
                        for b in cls.bases
                        if isinstance(b, (ast.Name, ast.Attribute))]
                # first definition wins within a class (rare; keeps
                # results deterministic)
                self.fns_raw[key].setdefault(fn.name, info)
        self._eff: Dict[Tuple[str, str],
                        Dict[str, _FunctionLockInfo]] = {}

    def _resolve(self, name: str,
                 from_path: str) -> Optional[Tuple[str, str]]:
        """The class key a base NAME refers to: same-file definition
        wins, else the first in path order (deterministic; cross-file
        mixins like ResilientEngineMixin are single-definition in
        practice)."""
        cands = self.by_name.get(name, [])
        if not cands:
            return None
        for k in cands:
            if k[0] == from_path:
                return k
        return min(cands)

    def ancestors(self, key: Tuple[str, str]) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        stack = [(b, key[0]) for b in self.bases.get(key, [])]
        while stack:
            bname, frm = stack.pop(0)
            k = self._resolve(bname, frm)
            if k is not None and k not in out and k != key:
                out.append(k)
                stack.extend((b, k[0]) for b in self.bases.get(k, []))
        return out

    def effective_fns(self, key: Tuple[str, str]
                      ) -> Dict[str, _FunctionLockInfo]:
        got = self._eff.get(key)
        if got is None:
            got = {}
            for anc in reversed(self.ancestors(key)):
                got.update(self.fns_raw.get(anc, {}))
            got.update(self.fns_raw.get(key, {}))
            self._eff[key] = got
        return got


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = ("lock-order inversions, same-lock re-acquisition, and "
                   "blocking calls under a held lock (direct or through "
                   "bounded transitive same-class calls)")

    def __init__(self, expansion_depth: int = EXPANSION_DEPTH):
        self.expansion_depth = expansion_depth

    def check(self, unit: AnalysisUnit):
        index = _ClassIndex(unit)
        # per-class lock graph: (file, class name) -> {(outer, inner):
        # site} — keyed like _ClassIndex so same-named classes in
        # different files keep separate graphs
        class_edges: Dict[Tuple[str, str],
                          Dict[Tuple[str, str],
                               Tuple[object, ast.AST, str]]] = {}

        for sf, qual, cls, fn, info in index.all_fns:
            # ---- blocking under lock + same-lock re-acquisition
            for held, call in info.calls_under_lock:
                blocking, why = _is_blocking_call(call, held)
                if blocking:
                    yield unit.finding(
                        sf, self.rule, call,
                        f"blocking call ({why}) while holding "
                        f"{' + '.join(held)} — fail futures/dispatch "
                        f"outside the lock (see "
                        f"AdmissionController.take)")
            for lk, site in info.relocks:
                yield unit.finding(
                    sf, self.rule, site,
                    f"re-acquisition of non-reentrant {lk} while "
                    f"already held — guaranteed deadlock")
            # ---- lexical nesting edges
            if cls is not None:
                edges = class_edges.setdefault((sf.path, cls.name), {})
                for outer, inner, site in info.nested:
                    edges.setdefault((outer, inner), (sf, site, qual))

            # ---- bounded transitive call expansion (same class,
            # ancestor methods included)
            if cls is None or not info.self_calls:
                continue
            ckey = (sf.path, cls.name)
            fns = index.effective_fns(ckey)
            edges = class_edges.setdefault(ckey, {})
            for held, callee, call in info.self_calls:
                reach = _reachable_facts(fns, callee, self.expansion_depth)
                if reach is None:
                    continue
                acquires, blocking, waits = reach
                for outer in held:
                    for inner, path in acquires.items():
                        via = " -> ".join(f"self.{p}()" for p in path)
                        if inner == outer:
                            yield unit.finding(
                                sf, self.rule, call,
                                f"{cls.name}.{fn.name} holds {outer} "
                                f"and calls {via}, which re-acquires "
                                f"{inner} — non-reentrant deadlock")
                        else:
                            edges.setdefault(
                                (outer, inner),
                                (sf, call,
                                 f"{cls.name}.{fn.name} -> {via}"))
                for why, path in blocking:
                    via = " -> ".join(f"self.{p}()" for p in path)
                    yield unit.finding(
                        sf, self.rule, call,
                        f"{cls.name}.{fn.name} holds "
                        f"{' + '.join(held)} and calls {via}, which "
                        f"blocks ({why}) — the lock is held for the "
                        f"whole wait (move the blocking call outside, "
                        f"or drop the lock first)")
                # a callee's Condition.wait is exempt in ITS body (wait
                # releases its own lock) but becomes the two-lock sleep
                # when this caller holds a DIFFERENT lock across the
                # chain — the held lock stays held for the whole wait
                for waitlock, path in waits.items():
                    for outer in held:
                        if outer == waitlock:
                            continue
                        via = " -> ".join(f"self.{p}()" for p in path)
                        yield unit.finding(
                            sf, self.rule, call,
                            f"{cls.name}.{fn.name} holds {outer} and "
                            f"calls {via}, which waits on {waitlock} — "
                            f"{outer} is held for the whole wait "
                            f"(two-lock sleep through the call chain)")

        # ---- cycles in each class's lock graph
        for (_path, cname), edges in class_edges.items():
            adj: Dict[str, Set[str]] = {}
            for (a, b) in edges:
                adj.setdefault(a, set()).add(b)
            for (a, b), (sf, site, where) in sorted(
                    edges.items(), key=lambda kv: (
                        kv[1][0].path, getattr(kv[1][1], "lineno", 0),
                        kv[0])):
                if self._reaches(adj, b, a):
                    yield unit.finding(
                        sf, self.rule, site,
                        f"lock-order inversion in {cname}: {a} -> {b} "
                        f"({where}) closes a cycle with the reverse "
                        f"ordering elsewhere — pick one global order")

    @staticmethod
    def _reaches(adj: Dict[str, Set[str]], src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False


# --------------------------------------------------------- static graph
def _normalize_node(cname: str, key: str) -> str:
    """'self._wd_lock' within class C -> 'C._wd_lock' — the node naming
    runtime lockdep also produces (instance class + attribute name), so
    the two graphs diff directly."""
    return f"{cname}.{key[5:] if key.startswith('self.') else key}"


def static_lock_graph(paths: List[str],
                      depth: int = EXPANSION_DEPTH) -> dict:
    """The static half of the lockdep differential: every lock-order
    edge the :class:`LockDisciplineChecker` derives (lexical nesting +
    bounded transitive same-class expansion), flattened to one edge set
    over ``Class.attr`` nodes. Base/mixin-class edges are projected
    onto every subclass in the unit as well — at runtime the locks
    belong to INSTANCES, and :mod:`tools.analysis.lockdep` names nodes
    by the instance's class, so ``ResilientEngineMixin``'s
    ``self._wd_lock`` nesting shows up dynamically as
    ``GenerationEngine._wd_lock``."""
    from tools.analysis.core import SourceFile, _collect_files

    files = []
    for fp in _collect_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                files.append(SourceFile(fp, f.read()))
        except (OSError, SyntaxError):
            continue
    unit = AnalysisUnit(files)
    index = _ClassIndex(unit)
    raw_edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for ckey in index.fns_raw:
        es = raw_edges.setdefault(ckey, set())
        fns = index.effective_fns(ckey)
        for fname, info in index.fns_raw[ckey].items():
            for outer, inner, _site in info.nested:
                es.add((outer, inner))
            for held, callee, _call in info.self_calls:
                reach = _reachable_facts(fns, callee, depth)
                if reach is None:
                    continue
                acquires, _blocking, _waits = reach
                for outer in held:
                    for inner in acquires:
                        if inner != outer:
                            es.add((outer, inner))
    edges: Set[Tuple[str, str]] = set()
    for ckey, es in raw_edges.items():
        # project base/mixin edges onto subclasses: runtime lockdep
        # names nodes by the INSTANCE's class
        holders = [ckey[1]] + [c[1] for c in index.fns_raw
                               if ckey in index.ancestors(c)]
        for holder in holders:
            for outer, inner in es:
                edges.add((_normalize_node(holder, outer),
                           _normalize_node(holder, inner)))
    return {"depth": depth,
            "edges": sorted([a, b] for a, b in edges)}
