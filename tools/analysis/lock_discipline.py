"""``lock-discipline``: the serving stack's locking contract, checked
from the AST.

The contract (written down in PR 1/3/7 review rounds, now enforced):

1. **No blocking under a held lock.** ``AdmissionController.take()``
   fails shed futures OUTSIDE its condition lock because
   ``Future.set_exception`` runs done-callbacks synchronously and a
   retry-on-shed callback re-entering the controller would deadlock.
   The same reasoning bans ``future.result()``, ``thread.join()``,
   ``time.sleep()``, and engine dispatch (``_dispatch`` /
   ``_guarded_run`` / ``_donated_call`` / the jitted executables /
   ``inject``) inside any ``with self._lock:`` region. A
   ``Condition.wait`` on the SAME lock is exempt (wait releases it);
   a wait on a different lock while holding one is the classic
   two-lock sleep and is flagged.
2. **No same-lock re-acquisition.** Every lock here is a non-reentrant
   ``threading.Lock``/``Condition`` — ``with self._lock:`` nested
   (lexically, or via a same-class method call) inside a region already
   holding ``self._lock`` is a guaranteed deadlock
   (``register_prefix`` inlines the ``_usable_blocks`` sum for exactly
   this reason).
3. **No lock-order inversions.** Nested acquisitions define edges in a
   per-class lock graph (A held while B is taken => A -> B); a cycle
   means two threads can deadlock. Edges come from lexical nesting plus
   ONE level of same-class call expansion (method f holds A and calls
   ``self.g()``; g acquires B).

Lock sites are recognized syntactically: ``with self.<attr>:`` where
the attribute name contains ``lock`` or ``cv`` (``_lock``, ``_wd_lock``,
``_prefix_lock``, ``_cv``, ...), plus bare local names matching the
same pattern.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import (
    AnalysisUnit, Checker, Finding, attr_chain, call_name, iter_functions,
)

#: Callees that block (or can block) the calling thread. Matched on the
#: FINAL attribute / bare name of the callee.
BLOCKING_ATTRS = {"result", "join"}
#: Dispatch-path callables: a device call under a lock stalls every
#: other thread that needs it for as long as XLA runs (or forever, if
#: the dispatch wedges — the watchdog would then deadlock against the
#: held lock too).
DISPATCH_CALLEES = {
    "_dispatch", "_run", "_guarded_run", "_retry_call", "_donated_call",
    "inject", "_prefill", "_decode", "_prefill_into", "_decode_iteration",
    "_prefill_prefix", "_fwd", "infer",
}
#: Receivers whose .join() is string/path joining, not thread joining.
SAFE_JOIN_RECEIVERS = {"os.path", "posixpath", "ntpath", "path"}


def is_lock_expr(node: ast.AST) -> Optional[str]:
    """The lock key when ``node`` looks like a lock object, else None.
    Keys are the dotted chain ('self._wd_lock'); bare names count too
    ('lock' locals in helpers)."""
    chain = attr_chain(node)
    if chain is None:
        return None
    last = chain.rsplit(".", 1)[-1].lower()
    if "lock" in last or last == "_cv" or last == "cv":
        return chain
    return None


def _with_locks(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        key = is_lock_expr(item.context_expr)
        if key is not None:
            out.append(key)
    return out


class _FunctionLockInfo:
    """Per-function lock facts: every lock the function acquires
    anywhere, and (lock, node, held-set) for each call made while at
    least one lock is held."""

    def __init__(self):
        self.acquires: Set[str] = set()
        # (held locks tuple, Call node) for calls under a lock
        self.calls_under_lock: List[Tuple[Tuple[str, ...], ast.Call]] = []
        # lexical nesting edges: (outer, inner, With node)
        self.nested: List[Tuple[str, str, ast.With]] = []
        # same-lock relock sites: (lock, With node)
        self.relocks: List[Tuple[str, ast.With]] = []
        # self-method calls under a lock: (held, method name, Call node)
        self.self_calls: List[Tuple[Tuple[str, ...], str, ast.Call]] = []


def _scan_function(fn: ast.FunctionDef) -> _FunctionLockInfo:
    info = _FunctionLockInfo()

    def walk(node, held: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # nested defs run later, not under this lock
            if isinstance(child, ast.With):
                # multi-item ``with a, b:`` acquires left to right: each
                # item is already held when the next acquires, so the
                # items order-edge (and relock-check) against each other
                # exactly like lexically nested with-statements
                cur = held
                for lk in _with_locks(child):
                    info.acquires.add(lk)
                    for outer in cur:
                        if outer == lk:
                            info.relocks.append((lk, child))
                        else:
                            info.nested.append((outer, lk, child))
                    cur = cur + (lk,)
                walk(child, cur)
                continue
            if isinstance(child, ast.Call) and held:
                info.calls_under_lock.append((held, child))
                chain = call_name(child)
                if chain is not None and chain.startswith("self.") \
                        and chain.count(".") == 1:
                    info.self_calls.append((held, chain.split(".", 1)[1],
                                            child))
            walk(child, held)

    walk(fn, ())
    return info


def _is_blocking_call(call: ast.Call, held: Tuple[str, ...]):
    """(True, why) when this call blocks under a held lock."""
    chain = call_name(call)
    if chain is None:
        return False, ""
    parts = chain.rsplit(".", 1)
    recv = parts[0] if len(parts) == 2 else ""
    last = parts[-1]
    if chain == "time.sleep" or last == "sleep":
        return True, "time.sleep"
    if last in BLOCKING_ATTRS:
        if last == "join" and (recv in SAFE_JOIN_RECEIVERS
                               or recv.endswith("path")):
            return False, ""
        # str.join on a literal separator: ", ".join(...)
        if last == "join" and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Constant):
            return False, ""
        return True, f".{last}()"
    if last == "wait":
        # Condition.wait on a HELD lock releases it while waiting — the
        # canonical pattern; waiting on anything else under a lock is
        # a two-lock sleep
        if recv in held:
            return False, ""
        if is_lock_expr(call.func.value if isinstance(call.func,
                                                      ast.Attribute)
                        else call.func) is not None:
            return True, f"wait on {recv or chain} while holding a " \
                         f"different lock"
        return False, ""
    if last in DISPATCH_CALLEES:
        return True, f"engine dispatch via {chain}()"
    if last == "get" and recv and ("queue" in recv.lower()
                                   or recv.endswith("_q")):
        return True, f"queue get on {recv}"
    return False, ""


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = ("lock-order inversions, same-lock re-acquisition, and "
                   "blocking calls under a held lock")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            # per-class lock graph: class name -> {(outer, inner): site}
            class_edges: Dict[str, Dict[Tuple[str, str],
                                        Tuple[ast.AST, str]]] = {}
            class_fn_info: Dict[str, Dict[str, _FunctionLockInfo]] = {}
            fn_infos: List[Tuple[str, Optional[ast.ClassDef],
                                 _FunctionLockInfo]] = []
            for qual, fn, cls in iter_functions(sf.tree):
                info = _scan_function(fn)
                fn_infos.append((qual, cls, info))
                if cls is not None:
                    class_fn_info.setdefault(cls.name, {})[fn.name] = info

            for qual, cls, info in fn_infos:
                # ---- blocking under lock + same-lock re-acquisition
                for held, call in info.calls_under_lock:
                    blocking, why = _is_blocking_call(call, held)
                    if blocking:
                        yield unit.finding(
                            sf, self.rule, call,
                            f"blocking call ({why}) while holding "
                            f"{' + '.join(held)} — fail futures/dispatch "
                            f"outside the lock (see "
                            f"AdmissionController.take)")
                for lk, site in info.relocks:
                    yield unit.finding(
                        sf, self.rule, site,
                        f"re-acquisition of non-reentrant {lk} while "
                        f"already held — guaranteed deadlock")
                # ---- lexical nesting edges
                if cls is not None:
                    edges = class_edges.setdefault(cls.name, {})
                    for outer, inner, site in info.nested:
                        edges.setdefault((outer, inner), (site, qual))

            # ---- one-level call expansion within each class
            for cname, fns in class_fn_info.items():
                edges = class_edges.setdefault(cname, {})
                for fname, info in fns.items():
                    for held, callee, call in info.self_calls:
                        target = fns.get(callee)
                        if target is None:
                            continue
                        for outer in held:
                            for inner in target.acquires:
                                if inner == outer:
                                    yield unit.finding(
                                        sf, self.rule, call,
                                        f"{cname}.{fname} holds {outer} "
                                        f"and calls self.{callee}(), "
                                        f"which re-acquires {inner} — "
                                        f"non-reentrant deadlock")
                                else:
                                    edges.setdefault(
                                        (outer, inner),
                                        (call, f"{cname}.{fname} -> "
                                               f"self.{callee}"))

            # ---- cycles in each class's lock graph
            for cname, edges in class_edges.items():
                adj: Dict[str, Set[str]] = {}
                for (a, b) in edges:
                    adj.setdefault(a, set()).add(b)
                for (a, b), (site, where) in sorted(
                        edges.items(), key=lambda kv: (
                            getattr(kv[1][0], "lineno", 0), kv[0])):
                    if self._reaches(adj, b, a):
                        yield unit.finding(
                            sf, self.rule, site,
                            f"lock-order inversion in {cname}: {a} -> {b} "
                            f"({where}) closes a cycle with the reverse "
                            f"ordering elsewhere — pick one global order")

    @staticmethod
    def _reaches(adj: Dict[str, Set[str]], src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False
