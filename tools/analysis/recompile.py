"""``recompile-risk``: protect the bounded-compiled-signature invariant
statically.

The serving stack's core perf contract (PR 1/2, asserted dynamically by
``compiled_signatures()`` tests): ALL executables an engine dispatches
come from the ``models/`` factories, and every shape that reaches one
is padded to the bucket ladder — so at most ``len(buckets)`` inference
signatures and ``len(prefill_buckets) + 1`` generation signatures ever
compile. Two ways new code breaks it:

1. **A stray ``jax.jit``/``pjit``/``pl.pallas_call`` callsite inside
   ``serving/``.** An executable minted in the serving layer escapes
   the factory conventions (donation, shardings, warmup, cache-size
   introspection) and is one ``lambda`` capture away from a
   per-request signature. Executables belong in ``models/`` factories;
   Pallas kernel launches belong in ``ops/`` kernel factories (e.g.
   ``paged_decode_attention``, which the paged decode factory routes
   through) — serving composes them.
2. **Shape-varying arguments that bypass the ladder.** An array built
   with a request-derived dimension (``prompt.size``, ``len(...)``,
   ``x.shape[...]``) fed straight to an executable compiles one
   signature per novel size. Every such construction must route the
   dimension through a bucket helper (``_bucket_for`` /
   ``bucket_ladder`` / ``prefill_buckets`` / ``blocks_for_tokens`` /
   ``tile_rows`` or the ``self.buckets`` ladder itself) first.

Rule 2 is scoped to functions that actually call an executable
(``self._prefill`` / ``self._decode`` / ``self._run`` /
``self._guarded_run`` / ``self._fwd`` / ``.infer``): array
constructions elsewhere can't create signatures.
"""
from __future__ import annotations

import ast
import os
from typing import Set

from tools.analysis.core import (
    AnalysisUnit, Checker, attr_chain, call_name, iter_functions,
    scoped_walk,
)

JIT_CALLEES = {"jax.jit", "jit", "pjit", "jax.pjit",
               # a pallas_call mints an executable just like jax.jit —
               # kernel launches live in the ops/ kernel factories
               # (FACTORY_DIRS), never inline in serving code
               "pallas_call", "pl.pallas_call"}
#: directories whose files may mint executables (factory homes)
FACTORY_DIRS = {"models", "nn", "ops", "autodiff", "parallel", "train"}
EXECUTABLE_CALLEES = {"_prefill", "_decode", "_run", "_guarded_run",
                      "_fwd", "infer"}
ARRAY_CTORS = {"zeros", "empty", "ones", "full"}
BUCKET_HELPERS = {"_bucket_for", "bucket_ladder", "prefill_buckets",
                  "blocks_for_tokens", "tile_rows"}


def _in_factory_dir(path: str) -> bool:
    parts = set(os.path.normpath(path).split(os.sep))
    return bool(parts & FACTORY_DIRS)


def _shape_is_request_derived(call: ast.Call) -> bool:
    """True when an array constructor's shape argument embeds a
    request-derived dimension: ``.size``, ``len(...)``, or a
    ``.shape[...]`` subscript."""
    shape_args = list(call.args[:1]) + [
        kw.value for kw in call.keywords if kw.arg == "shape"]
    for arg in shape_args:
        for node in ast.walk(arg):
            if isinstance(node, ast.Attribute) and node.attr == "size":
                return True
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "len":
                return True
            if isinstance(node, ast.Subscript):
                chain = attr_chain(node.value)
                if chain is not None and chain.endswith(".shape"):
                    return True
    return False


class RecompileRiskChecker(Checker):
    rule = "recompile-risk"
    description = ("jax.jit/pjit callsites outside models/ factories, and "
                   "request-shaped arguments bypassing the bucket ladder")

    def check(self, unit: AnalysisUnit):
        for sf in unit.files:
            factory_file = _in_factory_dir(sf.path)
            if not factory_file:
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.Call) \
                            and (call_name(node) or "") in JIT_CALLEES:
                        yield unit.finding(
                            sf, self.rule, node,
                            f"{call_name(node)}() callsite outside the "
                            f"models//ops/ factories — serving code "
                            f"composes executables, it does not mint "
                            f"them; move this into a make_* (or kernel) "
                            f"factory so donation/sharding/warmup "
                            f"conventions (and the len(buckets)+1 "
                            f"signature bound) hold")
            for qual, fn, _cls in iter_functions(sf.tree):
                yield from self._check_shapes(unit, sf, qual, fn)

    def _check_shapes(self, unit, sf, qual, fn):
        # constructions are collected PER SCOPE (nested defs are their
        # own iter_functions entries — a plain walk would double-report
        # them), but the executable/helper flags scan the whole subtree:
        # a retry closure dispatching the executable makes its enclosing
        # function's raw-shaped arrays just as dangerous
        calls_executable = False
        calls_helper = False
        ctor_sites = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = call_name(node)
            if chain is None:
                continue
            last = chain.rsplit(".", 1)[-1]
            if last in EXECUTABLE_CALLEES:
                calls_executable = True
            if last in BUCKET_HELPERS:
                calls_helper = True
        for node in scoped_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = call_name(node)
            if chain is None:
                continue
            if chain.rsplit(".", 1)[-1] in ARRAY_CTORS \
                    and _shape_is_request_derived(node):
                ctor_sites.append((node, chain))
        # reading self.buckets counts as using the ladder (warmup iterates
        # the rungs directly)
        if not calls_helper:
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and node.attr == "buckets":
                    calls_helper = True
                    break
        if not (calls_executable and ctor_sites) or calls_helper:
            return
        for node, chain in ctor_sites:
            yield unit.finding(
                sf, self.rule, node,
                f"{chain}() builds an array with a request-derived "
                f"dimension in {qual}, which also dispatches an "
                f"executable, without routing through a bucket helper "
                f"({'/'.join(sorted(BUCKET_HELPERS))}) — every novel "
                f"size compiles a fresh signature")
