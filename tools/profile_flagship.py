"""Per-component HBM-traffic / step-time breakdown of the flagship bench step.

VERDICT r3 task #1 demands either >=160k tok/s or "a committed per-op
HBM-traffic breakdown proving sustained bandwidth at the roofline". This
script produces that evidence two ways:

1. **XLA cost analysis** of the compiled train step (flops, bytes accessed)
   -> sustained HBM bandwidth = bytes / measured step time.
2. **Ablation timings**: recompile the step with one component neutered at a
   time (loss head -> mean(hidden); attention -> identity; fp32 softmax; no
   AdamW; fwd-only). The step-time delta attributes wall-clock to components
   far more honestly than eyeballing HLO, because it includes every fusion
   side effect.

Usage:  python tools/profile_flagship.py [--steps 10] [--out BASELINE_r4_profile.json]
Writes a JSON artifact (committed to the repo as the roofline proof).
"""
import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")


def _build(variant: str):
    """Return (step, params, opt_state, batch) for a named step variant."""
    import optax
    from deeplearning4j_tpu.models import (
        TransformerConfig, init_params)
    from deeplearning4j_tpu.models import bert as bert_mod

    # baseline == the shipped bench.py config (packed VMEM attention
    # kernel, fp32 softmax default) — keep these two in lockstep so the
    # committed artifact attributes the config the bench actually runs
    cfg = TransformerConfig(remat=False, attention_impl="flash")
    B, T = 96, 512
    if variant == "xla_attention":
        # round-3 shipped config: XLA fused attention, bf16 softmax
        cfg = TransformerConfig(remat=False, softmax_dtype=jnp.bfloat16)
    elif variant == "xla_softmax_fp32":
        # XLA attention with fp32 softmax — vs xla_attention isolates the
        # softmax dtype on the einsum path (attention impl held constant)
        cfg = TransformerConfig(remat=False, softmax_dtype=jnp.float32)
    elif variant == "kernel_softmax_bf16":
        # packed kernel with bf16 probabilities — vs baseline isolates
        # p_dtype on the kernel path (attention impl held constant)
        cfg = TransformerConfig(remat=False, attention_impl="flash",
                                softmax_dtype=jnp.bfloat16)

    key = jax.random.PRNGKey(0)
    params = init_params(key, cfg)
    tx = optax.adamw(1e-4, weight_decay=0.01)
    opt_state = tx.init(params)

    def ident_block(bp, x):
        # qkv + out-proj matmuls kept (FLOPs preserved), score matmuls +
        # softmax removed: isolates the (T,T) attention-interior cost
        h = bert_mod._layernorm(x, bp["ln1"])
        qkv = h @ bp["qkv"]["kernel"].astype(h.dtype) \
            + bp["qkv"]["bias"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        o = q + k + v
        x = x + o @ bp["attn_out"]["kernel"].astype(o.dtype) \
            + bp["attn_out"]["bias"].astype(o.dtype)
        h = bert_mod._layernorm(x, bp["ln2"])
        h = h @ bp["mlp_in"]["kernel"].astype(h.dtype) \
            + bp["mlp_in"]["bias"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        return x + h @ bp["mlp_out"]["kernel"].astype(h.dtype) \
            + bp["mlp_out"]["bias"].astype(h.dtype)

    def loss_fn(p, batch):
        # ablations reuse bert.encode/loss_from_logits so they cannot
        # desynchronize from the real forward/loss
        if variant == "no_losshead":
            x = bert_mod.encode(p, batch["tokens"], cfg, None)
            return x.astype(jnp.float32).mean()
        if variant == "no_attention":
            x = bert_mod.encode(p, batch["tokens"], cfg, None,
                                block_fn=ident_block)
            with jax.default_matmul_precision("default"):
                logits = x @ p["lm_head"].astype(x.dtype)
            return bert_mod.loss_from_logits(logits, batch)
        return bert_mod.lm_loss(p, batch, cfg, None)

    if variant == "fwd_only":
        def step(p, s, batch):
            return p, s, loss_fn(p, batch)
    elif variant == "no_adamw":
        def step(p, s, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            # sgd in place of adamw: isolates optimizer-state traffic
            p = jax.tree.map(lambda a, g: a - 1e-4 * g, p, grads)
            return p, s, loss
    else:
        def step(p, s, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            updates, s = tx.update(grads, s, p)
            import optax as _o
            p = _o.apply_updates(p, updates)
            return p, s, loss

    # analysis: ok recompile-risk — standalone bench/profiling harness: mints its own executables by design, never on a serving dispatch path
    jstep = jax.jit(step, donate_argnums=(0, 1))

    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "weights": jnp.ones((B, T), jnp.float32),
    }
    # analytic flops/token from the LIVE param pytree + the actual T (the
    # same shared helpers as bench.py — derived, not hand-expanded, so it
    # cannot drift from the step _build actually runs)
    from deeplearning4j_tpu.profiler.profiler import (
        non_embedding_params, transformer_flops_per_token)
    fpt = transformer_flops_per_token(
        non_embedding_params(params, cfg), cfg.layers, cfg.hidden, T)
    return jstep, params, opt_state, batch, B * T, fpt


def _time_variant(variant: str, steps: int, warmup: int = 3):
    jstep, params, opt_state, batch, ntok, fpt = _build(variant)
    lowered = jstep.lower(params, opt_state, batch)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_acc = float(cost.get("bytes accessed", 0.0))

    for _ in range(warmup):
        params, opt_state, loss = jstep(params, opt_state, batch)
    float(loss)
    # median of 3 windows, mirroring bench.py: the axon tunnel adds ±3%
    # per-window noise that would otherwise masquerade as variant deltas
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = jstep(params, opt_state, batch)
        float(loss)
        dts.append((time.perf_counter() - t0) / steps)
    dt = sorted(dts)[1]
    # both MFU bases side by side (round-5 verdict #5): the headline uses
    # the analytic basis (profiler.MFU_BASIS, same as bench.py, computed
    # from the live params in _build); mfu_xla divides XLA's implementation-
    # flop count by peak — a few points lower is expected, not a discrepancy
    from deeplearning4j_tpu.profiler.profiler import mfu as _mfu, peak_flops
    peak = peak_flops(jax.devices()[0])
    row = {
        "variant": variant,
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_sec": round(ntok / dt, 0),
        "xla_flops": flops,
        "xla_bytes_accessed": bytes_acc,
        "sustained_gbps": round(bytes_acc / dt / 1e9, 1),
        "achieved_tflops": round(flops / dt / 1e12, 2),
        "mfu_xla": round(flops / dt / peak, 4),
    }
    if variant in ("baseline", "xla_attention", "xla_softmax_fp32",
                   "kernel_softmax_bf16"):
        # analytic MFU only where the variant runs the FULL train step —
        # ablated steps do fewer model flops than the analytic count assumes
        row["mfu_analytic"] = round(_mfu(ntok / dt, fpt, peak), 4)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default=None)
    ap.add_argument("--variants", default="baseline,xla_attention,fwd_only,no_losshead,no_attention,no_adamw,xla_softmax_fp32,kernel_softmax_bf16")
    args = ap.parse_args()

    results = []
    for v in args.variants.split(","):
        r = _time_variant(v.strip(), args.steps)
        results.append(r)
        print(json.dumps(r), flush=True)

    base = next((r for r in results if r["variant"] == "baseline"), None)
    summary = {"device": str(jax.devices()[0]), "results": results}
    if base:
        deltas = {}
        for r in results:
            if r["variant"] != "baseline":
                deltas[r["variant"]] = {
                    "step_ms_delta": round(base["step_ms"] - r["step_ms"], 2),
                    "bytes_delta_gb": round(
                        (base["xla_bytes_accessed"] - r["xla_bytes_accessed"]) / 1e9, 2),
                }
        summary["deltas_vs_baseline"] = deltas
    print(json.dumps(summary.get("deltas_vs_baseline", {}), indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)


if __name__ == "__main__":
    main()
