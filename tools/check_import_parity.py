"""BERT-scale loss parity: TF-imported fine-tune under computeDtype=HALF
(bf16 compute / fp32 masters) vs FLOAT, identical data and init.

The round-2 verdict's done-criterion for config #4: "parity vs fp32 within
loss tolerance at B=32/T=128, recorded in BASELINE.md". Run on the TPU:

    python tools/check_import_parity.py [--steps 30]

Prints per-step losses for both dtypes and the max |rel diff|, then a
PASS/FAIL against --rtol (default 0.02: bf16 matmul rounding accumulates
~1e-3/step on this workload; 2% headroom keeps the check meaningful without
flaking).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np


def run(dtype: str, steps: int):
    from deeplearning4j_tpu.autodiff import TrainingConfig
    from deeplearning4j_tpu.train import Adam
    from deeplearning4j_tpu.modelimport.tensorflow import TensorflowFrameworkImporter
    from tools.tf_bert import build_frozen_bert

    L, H, A, V, T, inter = 12, 768, 12, 30522, 128, 3072
    B = 32
    gd, in_name, out_name, _ = build_frozen_bert(L=L, H=H, A=A, V=V, T=T,
                                                 intermediate=inter)
    sd = TensorflowFrameworkImporter.runImport(gd)
    sd.convertAllConstantsToVariables()
    hidden = sd.getVariable(out_name)
    lm_w = sd.var("lm_head", (H, V), weightInit="XAVIER")
    logits = sd.linalg.matmul(hidden, lm_w)
    targets = sd.placeHolder("targets", shape=(B, T), dtype=jnp.int32)
    loss = sd.loss.sparseMcxent(targets, logits)
    sd.setLossVariables(loss.name)
    sd.setTrainingConfig(TrainingConfig(
        updater=Adam(1e-4),
        computeDtype="BFLOAT16" if dtype == "HALF" else None))

    rng = np.random.default_rng(7)
    batches = [{in_name: rng.integers(0, V, (B, T)).astype(np.int32),
                "targets": rng.integers(0, V, (B, T)).astype(np.int32)}
               for _ in range(steps)]
    return sd.fit(batches)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--rtol", type=float, default=0.02)
    args = ap.parse_args()

    h32 = np.asarray(run("FLOAT", args.steps))
    h16 = np.asarray(run("HALF", args.steps))
    rel = np.abs(h16 - h32) / np.maximum(np.abs(h32), 1e-9)
    out = {
        "steps": args.steps,
        "fp32_first_last": [round(float(h32[0]), 5), round(float(h32[-1]), 5)],
        "bf16_first_last": [round(float(h16[0]), 5), round(float(h16[-1]), 5)],
        "max_rel_diff": round(float(rel.max()), 5),
        "rtol": args.rtol,
        "pass": bool(rel.max() < args.rtol),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
