"""Fleet chaos soak orchestrator (ISSUE 18): scheduled, seeded episodes
of failure against a live generation fleet under trace-driven load,
gated by the zero-leak resource ledger.

Composes ONLY existing primitives — nothing here invents a new failure
mode, it schedules the proven ones:

- **kill** — abrupt host death. In-process fleets sever the host's
  HTTP server and hard-stop its engine (the test_rpc.py kill idiom);
  subprocess fleets SIGKILL a real OS process (the PR 15 soak,
  generalized). Either way the front door's hedged re-dispatch must
  land every in-flight stream on a survivor, watermark-clean.
- **drain** — the graceful opposite: ``drain_host`` (mark → finish
  residents → leave), then the host is recycled (leave + join = the
  elasticity churn loop at episode cadence).
- **preempt_storm** — a clump of interactive streams aimed at a pool
  sized to starve: on-demand block allocation must preempt batch
  residents (swap-out above the crossover, recompute below).
- **swap_pressure** — the storm with a seeded ``kv.swap_*`` fault plan
  layered on: delayed swap-outs, failed swap-ins (the DEGRADE path —
  recompute, never a shed).
- **rpc_faults** — a seeded ``rpc.*`` plan over the load window:
  dispatch failures, stream losses, slow responses; hedging absorbs.

The schedule is a pure function of its seed (:class:`ChaosSchedule.
generate`) — same seed, bit-identical episode script; an incident
replays from one integer. After every episode the harness probes
recovery-to-SLO, and at the end the :class:`~.serving.ledger.
ResourceLedger` must read flat: zero stuck streams, zero leaked
blocks/ops/threads, RSS back to baseline slack.

CLI (in-process fleet on the seeded tiny model)::

    python -m tools.soak --seed 7 --n-hosts 3 --duration-s 20

prints the :class:`SoakReport` as one JSON line (the bench contract).
"""
from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

EPISODE_KINDS = ("kill", "drain", "preempt_storm", "swap_pressure",
                 "rpc_faults")


def _rng(seed: int, label: str) -> np.random.Generator:
    return np.random.default_rng([int(seed), zlib.crc32(label.encode())])


# ------------------------------------------------------------------ schedule
@dataclasses.dataclass(frozen=True)
class Episode:
    """One scheduled chaos event: ``at_s`` on the soak clock, ``kind``
    from :data:`EPISODE_KINDS`, ``target`` a host slot index, and the
    fault window's ``duration_s`` (fault-plan episodes stay installed
    that long; kill/drain act once and use it as the settle window)."""

    index: int
    at_s: float
    kind: str
    target: int
    duration_s: float


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A seeded episode script. ``generate()`` is pure in (seed,
    duration_s, n_hosts, kinds): equality of two schedules IS the
    bit-for-bit replay contract the acceptance test asserts."""

    seed: int
    duration_s: float
    n_hosts: int
    episodes: Tuple[Episode, ...]

    @classmethod
    def generate(cls, seed: int, *, duration_s: float, n_hosts: int,
                 kinds: Sequence[str] = EPISODE_KINDS,
                 start_s: float = 1.0,
                 mean_gap_s: float = 2.0) -> "ChaosSchedule":
        """Seeded schedule: exponential gaps from ``start_s``, every
        requested kind guaranteed at least once (cycled before random
        fill), targets drawn uniformly over host slots. Episodes stop
        at 90% of the horizon so the tail of the soak observes
        RECOVERY, not fresh damage."""
        for k in kinds:
            if k not in EPISODE_KINDS:
                raise ValueError(f"unknown episode kind {k!r}")
        rng = _rng(seed, "soak.schedule")
        horizon = duration_s * 0.9
        episodes: List[Episode] = []
        t = start_s
        while t < horizon:
            kind = kinds[len(episodes) % len(kinds)] \
                if len(episodes) < len(kinds) \
                else kinds[int(rng.integers(len(kinds)))]
            episodes.append(Episode(
                index=len(episodes), at_s=round(float(t), 3), kind=kind,
                target=int(rng.integers(n_hosts)),
                duration_s=round(float(rng.uniform(0.5, 1.5)), 3)))
            t += float(rng.exponential(mean_gap_s))
        return cls(seed=seed, duration_s=duration_s, n_hosts=n_hosts,
                   episodes=tuple(episodes))

    def to_dict(self) -> dict:
        return {"seed": self.seed, "duration_s": self.duration_s,
                "n_hosts": self.n_hosts,
                "episodes": [dataclasses.asdict(e)
                             for e in self.episodes]}


# -------------------------------------------------------------------- fleets
class InProcessFleet:
    """≥3 real HTTP hosts over the PR 12 RPC plane, one process.

    Every data-plane byte crosses a loopback TCP socket (HostRpcServer
    + RemoteHost — the wire IS the wire); only the host *processes* are
    simulated, which is what lets kill/respawn cycle in CI time. The
    subprocess variant for multi-process realism is
    :class:`SubprocessFleet`.

    ``make_engine(slot)`` builds one GenerationEngine per host slot —
    the soak passes a starved on-demand pool with a swap store so
    preemption storms and swap pressure have something to starve.
    """

    def __init__(self, make_engine: Callable[[int], object],
                 n_hosts: int = 3, *, tracer=None, hedge=None,
                 heartbeat_timeout_s: float = 300.0):
        from deeplearning4j_tpu.serving import (
            ClusterDirectory, ClusterFrontDoor, HedgePolicy,
        )

        self.make_engine = make_engine
        self.n_hosts = n_hosts
        self.directory = ClusterDirectory(
            heartbeat_timeout_s=heartbeat_timeout_s)
        self._slots: List[Optional[dict]] = [None] * n_hosts
        self._next_id = 0
        for i in range(n_hosts):
            self._start_host(i)
        self.front_door = ClusterFrontDoor(
            self.directory, tracer=tracer,
            hedge=hedge if hedge is not None else HedgePolicy(
                hedge_after_ms=None, max_attempts=4, poll_wait_ms=25.0))

    def _start_host(self, slot: int):
        from deeplearning4j_tpu.serving import (
            HeartbeatPump, HostRpcServer, LoopbackHost, LoopbackTransport,
            RemoteHost,
        )

        host_id = self._next_id
        self._next_id += 1
        engine = self.make_engine(slot)
        local = LoopbackHost(host_id, generation=engine)
        srv = HostRpcServer(local)
        rem = RemoteHost(host_id, srv.url)
        self.directory.join(rem)
        HeartbeatPump(rem, LoopbackTransport(self.directory)).pump_once()
        self._slots[slot] = {"host_id": host_id, "engine": engine,
                             "local": local, "srv": srv, "rem": rem}

    # ---------------------------------------------------------- primitives
    def engines(self) -> list:
        return [s["engine"] for s in self._slots if s is not None]

    def servers(self) -> list:
        return [s["srv"] for s in self._slots if s is not None]

    def kill(self, slot: int):
        """Abrupt host death: server severed, engine hard-stopped, no
        drain — resident streams must recover via hedged re-dispatch."""
        s = self._slots[slot]
        if s is None:
            return
        self._slots[slot] = None
        s["srv"].stop()
        s["local"].shutdown(wait=False)
        self.directory.leave(s["host_id"])

    def drain(self, slot: int, timeout: Optional[float] = 30.0) -> bool:
        """Graceful recycle half: mark → finish residents → leave."""
        from deeplearning4j_tpu.serving import drain_host

        s = self._slots[slot]
        if s is None:
            return True
        ok = drain_host(self.directory, s["host_id"], timeout=timeout)
        self._slots[slot] = None
        s["srv"].stop()
        s["local"].shutdown()
        return ok

    def respawn(self, slot: int):
        """Elasticity churn's join half: a FRESH engine behind a fresh
        port joins under a fresh host id."""
        if self._slots[slot] is None:
            self._start_host(slot)

    def shutdown(self):
        for slot, s in enumerate(self._slots):
            if s is None:
                continue
            self._slots[slot] = None
            s["srv"].stop()
            s["local"].shutdown()


class SubprocessFleet:
    """Real OS processes behind the same surface: each host is a child
    python building the seeded tiny model + GenerationEngine +
    HostRpcServer (the PR 15 worker, generalized to a fleet), so
    ``kill`` is a genuine SIGKILL — kernel-reaped sockets, no goodbye.

    The long soak (tests/test_soak.py, ``soak+slow``) runs on this;
    child warmup is tens of seconds each, which is why the tier-1
    smoke uses :class:`InProcessFleet`.
    """

    WORKER = """
import sys
import time

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models import TransformerConfig, init_params
from deeplearning4j_tpu.serving import (
    GenerationEngine, HostRpcServer, LoopbackHost,
)

slot = int(sys.argv[1])
cfg = TransformerConfig(vocab_size=50, hidden=32, layers=2, heads=2,
                        mlp_dim=64, max_seq=64, dtype=jnp.float32,
                        causal=True, attention_impl="full", remat=False)
params = init_params(jax.random.PRNGKey(0), cfg)
g = GenerationEngine(params, cfg, slots=2, max_len=48,
                     allocate="on_demand", swap_threshold_blocks=1,
                     name="soak-host%d" % slot)
local = LoopbackHost(slot, generation=g)
srv = HostRpcServer(local)
print("URL " + srv.url, flush=True)
while True:          # serve until SIGKILLed — no graceful exit path
    time.sleep(1.0)
"""

    def __init__(self, workdir, repo_root, n_hosts: int = 3, *,
                 tracer=None, hedge=None,
                 heartbeat_timeout_s: float = 300.0,
                 spawn_timeout_s: float = 300.0):
        from deeplearning4j_tpu.serving import (
            ClusterDirectory, ClusterFrontDoor, HedgePolicy,
        )

        self.workdir = workdir
        self.repo_root = repo_root
        self.n_hosts = n_hosts
        self.spawn_timeout_s = spawn_timeout_s
        self.directory = ClusterDirectory(
            heartbeat_timeout_s=heartbeat_timeout_s)
        self._slots: List[Optional[dict]] = [None] * n_hosts
        self._next_id = 0
        for i in range(n_hosts):
            self._start_host(i)
        self.front_door = ClusterFrontDoor(
            self.directory, tracer=tracer,
            hedge=hedge if hedge is not None else HedgePolicy(
                hedge_after_ms=None, max_attempts=4, poll_wait_ms=25.0))

    def _spawn(self, host_id: int):
        import os
        import subprocess
        import sys
        from pathlib import Path

        script = Path(self.workdir) / "soak_host.py"
        if not script.exists():
            script.write_text(self.WORKER)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = str(self.repo_root) + os.pathsep \
            + env.get("PYTHONPATH", "")
        return subprocess.Popen(
            [sys.executable, str(script), str(host_id)],
            cwd=str(self.repo_root), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    @staticmethod
    def _read_url(child, deadline_s: float) -> str:
        out: List[str] = []

        def reader():
            for line in child.stdout:
                out.append(line.rstrip("\n"))
                if line.startswith("URL "):
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        t.join(timeout=deadline_s)
        for line in out:
            if line.startswith("URL "):
                return line[4:].strip()
        raise RuntimeError(
            "soak host %s never published its URL:\n%s"
            % (child.pid, "\n".join(out)))

    def _start_host(self, slot: int):
        from deeplearning4j_tpu.serving import (
            HeartbeatPump, LoopbackTransport, RemoteHost,
        )

        host_id = self._next_id
        self._next_id += 1
        child = self._spawn(host_id)
        url = self._read_url(child, self.spawn_timeout_s)
        rem = RemoteHost(host_id, url)
        self.directory.join(rem)
        HeartbeatPump(rem, LoopbackTransport(self.directory)).pump_once()
        self._slots[slot] = {"host_id": host_id, "child": child,
                             "rem": rem}

    # ---------------------------------------------------------- primitives
    def engines(self) -> list:
        return []    # engine internals live in the children

    def servers(self) -> list:
        return []

    def kill(self, slot: int):
        import signal

        s = self._slots[slot]
        if s is None:
            return
        self._slots[slot] = None
        s["child"].send_signal(signal.SIGKILL)
        s["child"].wait(timeout=30)
        self.directory.leave(s["host_id"])

    def drain(self, slot: int, timeout: Optional[float] = 60.0) -> bool:
        from deeplearning4j_tpu.serving import drain_host

        s = self._slots[slot]
        if s is None:
            return True
        ok = drain_host(self.directory, s["host_id"], timeout=timeout)
        self._slots[slot] = None
        s["child"].kill()
        s["child"].wait(timeout=30)
        return ok

    def respawn(self, slot: int):
        if self._slots[slot] is None:
            self._start_host(slot)

    def shutdown(self):
        for slot, s in enumerate(self._slots):
            if s is None:
                continue
            self._slots[slot] = None
            s["child"].kill()
            s["child"].wait(timeout=30)


# ------------------------------------------------------------------- harness
@dataclasses.dataclass
class EpisodeResult:
    episode: Episode
    started_t: float
    ended_t: float
    recovery_to_slo_s: Optional[float] = None
    note: str = ""

    def window(self) -> Tuple[float, float]:
        end = self.ended_t
        if self.recovery_to_slo_s is not None:
            end = max(end, self.started_t + self.recovery_to_slo_s)
        return (self.started_t, end)


class SoakReport:
    """Everything the bench leg and the acceptance test read: the
    replayable schedule, per-episode recovery, the load report split
    during/between episodes, and the ledger verdict."""

    def __init__(self, schedule: ChaosSchedule,
                 episodes: List[EpisodeResult], load_report,
                 ledger_violations: List[str]):
        self.schedule = schedule
        self.episodes = episodes
        self.load_report = load_report
        self.ledger_violations = ledger_violations

    @property
    def ledger_clean(self) -> bool:
        return not self.ledger_violations

    def recovery_times_s(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.episodes:
            if r.recovery_to_slo_s is not None:
                key = f"{r.episode.kind}#{r.episode.index}"
                out[key] = round(r.recovery_to_slo_s, 3)
        return out

    def to_dict(self) -> dict:
        windows = [r.window() for r in self.episodes]
        load = self.load_report.to_dict(windows=windows)
        rec = self.recovery_times_s()
        return {
            "schedule": self.schedule.to_dict(),
            "episodes_fired": len(self.episodes),
            "load": load,
            "recovery_to_slo_s": rec,
            "max_recovery_to_slo_s": max(rec.values()) if rec else None,
            "ledger_clean": self.ledger_clean,
            "ledger_violations": self.ledger_violations,
        }


class SoakHarness:
    """Runs one soak: trace-driven load over the fleet's front door
    while the seeded schedule fires episodes, then gates on the ledger.

    ``fleet`` is an :class:`InProcessFleet` / :class:`SubprocessFleet`
    (anything with front_door/engines/servers/kill/drain/respawn).
    ``slo_latency_ms`` defines recovered-to-SLO for the post-kill/drain
    probe loop. The harness owns the ledger: baseline right after
    warmup, verdict after the fleet is idle again.
    """

    def __init__(self, fleet, schedule: ChaosSchedule, spec, *,
                 slo_latency_ms: float = 2_000.0,
                 probe_timeout_s: float = 30.0,
                 ledger=None, storm_streams: int = 4,
                 drain_timeout_s: float = 120.0):
        self.fleet = fleet
        self.schedule = schedule
        self.spec = spec
        self.slo_latency_ms = slo_latency_ms
        self.probe_timeout_s = probe_timeout_s
        self.storm_streams = storm_streams
        self.drain_timeout_s = drain_timeout_s
        if ledger is None:
            from deeplearning4j_tpu.serving.ledger import ResourceLedger

            ledger = ResourceLedger(engines=fleet.engines(),
                                    rpc_servers=fleet.servers(),
                                    front_doors=[fleet.front_door])
        self.ledger = ledger

    # -------------------------------------------------------------- pieces
    def _probe_prompt(self) -> np.ndarray:
        rng = _rng(self.schedule.seed, "soak.probe")
        return rng.integers(1, self.spec.vocab_size, 4).astype(np.int32)

    def warmup(self):
        """Compile every host's executables before the baseline — XLA
        compilation is a one-time RSS step the flat-memory gate must
        not attribute to chaos."""
        p = self._probe_prompt()
        for i in range(self.fleet.n_hosts):
            self.fleet.front_door.submit_generate(
                p, max_new_tokens=2, seed=1, host=None).result(timeout=300)

    def _probe_recovery(self, t_from: float) -> Optional[float]:
        """Seconds from ``t_from`` until one probe stream completes
        within the SLO; None if the window expires first."""
        p = self._probe_prompt()
        deadline = time.monotonic() + self.probe_timeout_s
        while time.monotonic() < deadline:
            t0 = time.perf_counter()
            try:
                self.fleet.front_door.submit_generate(
                    p, max_new_tokens=2, seed=2,
                    priority="interactive").result(
                        timeout=self.probe_timeout_s)
            except Exception:
                time.sleep(0.05)
                continue
            if (time.perf_counter() - t0) * 1e3 <= self.slo_latency_ms:
                return time.perf_counter() - t_from
            time.sleep(0.05)
        return None

    def _storm(self, rng: np.random.Generator, n: int):
        """A clump of interactive streams big enough to starve the
        pool: on-demand allocation must preempt batch residents. Fire
        and forget — their terminals land in their own callbacks."""
        cap = self.spec.max_len
        for _ in range(n):
            plen = int(rng.integers(cap // 3, cap // 2))
            prompt = rng.integers(1, self.spec.vocab_size,
                                  plen).astype(np.int32)
            try:
                self.fleet.front_door.submit_generate(
                    prompt, max_new_tokens=int(rng.integers(8, cap // 3)),
                    seed=int(rng.integers(2 ** 31)),
                    tenant="storm", priority="interactive")
            except Exception:
                pass   # a shed storm stream is pressure working as intended

    def _run_episode(self, ep: Episode,
                     rng: np.random.Generator) -> EpisodeResult:
        from deeplearning4j_tpu.serving import FaultPlan

        t0 = time.perf_counter()
        recovery = None
        note = ""
        slot = ep.target % self.fleet.n_hosts
        if ep.kind == "kill":
            self.fleet.kill(slot)
            self.fleet.respawn(slot)
            recovery = self._probe_recovery(t0)
        elif ep.kind == "drain":
            ok = self.fleet.drain(slot)
            note = "drained" if ok else "drain timed out"
            self.fleet.respawn(slot)
            recovery = self._probe_recovery(t0)
        elif ep.kind == "preempt_storm":
            self._storm(rng, self.storm_streams)
            time.sleep(ep.duration_s)
        elif ep.kind == "swap_pressure":
            plan = (FaultPlan(seed=self.schedule.seed + ep.index)
                    .delay("kv.swap_out", 5.0, rate=0.5)
                    .fail("kv.swap_in", rate=0.25))
            with plan:
                self._storm(rng, self.storm_streams)
                time.sleep(ep.duration_s)
            note = f"{len(plan.fired())} swap fault(s) fired"
        elif ep.kind == "rpc_faults":
            plan = (FaultPlan(seed=self.schedule.seed + ep.index)
                    .fail("rpc.dispatch", rate=0.15)
                    .fail("rpc.stream", rate=0.1)
                    .delay("rpc.response", 10.0, rate=0.2))
            with plan:
                time.sleep(ep.duration_s)
            note = f"{len(plan.fired())} rpc fault(s) fired"
        return EpisodeResult(episode=ep, started_t=t0,
                             ended_t=time.perf_counter(),
                             recovery_to_slo_s=recovery, note=note)

    # ----------------------------------------------------------------- run
    def run(self) -> SoakReport:
        from deeplearning4j_tpu.serving.loadgen import (
            LoadGenerator, front_door_submitter,
        )

        self.warmup()
        self.ledger.baseline()
        rng = _rng(self.schedule.seed, "soak.episodes")
        gen = LoadGenerator(self.spec.generate(),
                            front_door_submitter(self.fleet.front_door),
                            drain_timeout_s=self.drain_timeout_s)
        load_out: List[object] = []
        load_thread = threading.Thread(
            target=lambda: load_out.append(gen.run()),
            name="soak-loadgen", daemon=True)
        t0 = time.perf_counter()
        load_thread.start()
        results: List[EpisodeResult] = []
        for ep in self.schedule.episodes:
            delay = (t0 + ep.at_s) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            results.append(self._run_episode(ep, rng))
        load_thread.join(timeout=self.schedule.duration_s
                         + self.drain_timeout_s + 60.0)
        report = load_out[0] if load_out else None
        if report is None:
            raise RuntimeError("load generator never finished")
        violations = self.ledger.check(timeout_s=30.0)
        return SoakReport(self.schedule, results, report, violations)


# ---------------------------------------------------------------------- CLI
def starved_engine_factory(tiny_model=None, *, slots: int = 2,
                           max_len: int = 48, num_blocks: int = 20,
                           tracer=None) -> Callable[[int], object]:
    """The soak's standard host engine: seeded tiny model, on-demand
    block allocation over a pool sized to starve under the storm, swap
    store armed above a 1-block crossover — the configuration where
    every chaos episode has teeth."""
    if tiny_model is None:
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models import TransformerConfig, init_params

        cfg = TransformerConfig(vocab_size=50, hidden=32, layers=2,
                                heads=2, mlp_dim=64, max_seq=64,
                                dtype=jnp.float32, causal=True,
                                attention_impl="full", remat=False)
        params = init_params(jax.random.PRNGKey(0), cfg)
    else:
        cfg, params = tiny_model

    def make_engine(slot: int):
        from deeplearning4j_tpu.serving import GenerationEngine

        return GenerationEngine(params, cfg, slots=slots, max_len=max_len,
                                allocate="on_demand", num_blocks=num_blocks,
                                swap_threshold_blocks=1, tracer=tracer,
                                name=f"soak-g{slot}")
    return make_engine


def run_soak(*, seed: int = 0, n_hosts: int = 3, duration_s: float = 20.0,
             rate_rps: float = 4.0, tiny_model=None,
             kinds: Sequence[str] = EPISODE_KINDS,
             mean_gap_s: float = 3.0) -> SoakReport:
    """One in-process soak end to end (the bench leg's entry point)."""
    from deeplearning4j_tpu.serving.loadgen import ArrivalProcess, TraceSpec

    fleet = InProcessFleet(starved_engine_factory(tiny_model),
                           n_hosts=n_hosts)
    try:
        schedule = ChaosSchedule.generate(seed, duration_s=duration_s,
                                          n_hosts=n_hosts, kinds=kinds,
                                          mean_gap_s=mean_gap_s)
        spec = TraceSpec(seed=seed, duration_s=duration_s,
                         arrival=ArrivalProcess(kind="onoff",
                                                rate_rps=rate_rps))
        return SoakHarness(fleet, schedule, spec).run()
    finally:
        fleet.shutdown()


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        description="Seeded fleet chaos soak (ISSUE 18)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n-hosts", type=int, default=3)
    ap.add_argument("--duration-s", type=float, default=20.0)
    ap.add_argument("--rate-rps", type=float, default=4.0)
    ap.add_argument("--kinds", default=",".join(EPISODE_KINDS),
                    help="comma-separated episode kinds")
    args = ap.parse_args(argv)
    report = run_soak(seed=args.seed, n_hosts=args.n_hosts,
                      duration_s=args.duration_s, rate_rps=args.rate_rps,
                      kinds=tuple(k for k in args.kinds.split(",") if k))
    print(json.dumps(report.to_dict()))
    return 0 if report.ledger_clean \
        and report.load_report.stuck_streams == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
