"""Regenerate golden trajectories for tests/test_golden_trajectories.py.

Run from the repo root: ``python tools/gen_goldens.py``. Forces the same
platform config as tests/conftest.py (8-device virtual CPU mesh, fp64) so
goldens are generated under the exact environment that replays them. Any
regeneration must be explained in the commit message (the reference's golden
update policy for dl4j-integration-tests).
"""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from test_golden_trajectories import CASES, GOLDEN_DIR, run_trajectory  # noqa: E402


def main():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    for name in sorted(CASES):
        losses, checksum, sq = run_trajectory(name)
        path = os.path.join(GOLDEN_DIR, f"{name}.json")
        with open(path, "w") as f:
            json.dump({"losses": losses, "param_abs_sum": checksum,
                       "param_sq_sum": sq}, f, indent=1)
        print(f"{name}: losses[0]={losses[0]:.6f} losses[-1]={losses[-1]:.6f} "
              f"abs_sum={checksum:.6f} -> {path}")


if __name__ == "__main__":
    main()
